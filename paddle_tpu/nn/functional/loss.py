"""Loss functionals.

reference parity: python/paddle/nn/functional/loss.py (phi cross_entropy /
bce / kldiv / … kernels). cross_entropy follows the reference's
softmax_with_cross_entropy semantics (soft/hard labels, ignore_index,
label smoothing) as one fused logsumexp expression — the form XLA fuses into
the preceding matmul on TPU.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...ops._apply import ensure_tensor
from ...tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "mse_loss", "l1_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "ctc_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "dice_loss", "npair_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "poisson_nll_loss", "gaussian_nll_loss",
]


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(input, label, weight=None, ignore_index: int = -100,
                  reduction: str = "mean", soft_label: bool = False,
                  axis: int = -1, use_softmax: bool = True,
                  label_smoothing: float = 0.0, name=None):
    """reference: functional/loss.py cross_entropy (phi cross_entropy_with_softmax)."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    has_w = weight is not None
    ins = [input]
    label_in = label if soft_label else Tensor(label._value, stop_gradient=True)
    ins.append(label_in)
    if has_w:
        ins.append(ensure_tensor(weight))

    def fn(logits, lbl, *wt):
        ax = axis if axis >= 0 else logits.ndim + axis
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=ax)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-12, None))
        nclass = logits.shape[ax]
        if soft_label:
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / nclass
            loss = -jnp.sum(soft * logp, axis=ax)
            if has_w:
                w_b = jnp.sum(soft * wt[0], axis=ax)
                loss = loss * w_b
            return _reduce(loss, reduction)
        idx = lbl.astype(jnp.int32)
        if idx.ndim == logits.ndim:  # trailing 1 dim
            idx = jnp.squeeze(idx, axis=ax)
        valid = idx != ignore_index
        safe_idx = jnp.where(valid, idx, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe_idx, ax), axis=ax
        ).squeeze(ax)
        if label_smoothing > 0:
            smooth_loss = -jnp.mean(logp, axis=ax)
            loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
        else:
            loss = -picked
        loss = jnp.where(valid, loss, 0.0)
        if has_w:
            w_per = jnp.take(wt[0], safe_idx)
            w_per = jnp.where(valid, w_per, 0.0)
            loss = loss * w_per
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w_per), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return apply_op(fn, ins, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label: bool = False,
                               ignore_index: int = -100, numeric_stable_mode: bool = True,
                               return_softmax: bool = False, axis: int = -1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps a trailing singleton dim on the hard-label path
    from .. import functional as F

    loss_keep = apply_op(lambda l: jnp.expand_dims(l, axis), [loss], name="unsqueeze") \
        if not soft_label else loss
    if return_softmax:
        sm = F.softmax(logits, axis=axis)
        return loss_keep, sm
    return loss_keep


def binary_cross_entropy(input, label, weight=None, reduction: str = "mean", name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    ins = [input, label]
    has_w = weight is not None
    if has_w:
        ins.append(ensure_tensor(weight))

    def fn(p, y, *wt):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if has_w:
            loss = loss * wt[0]
        return _reduce(loss, reduction)

    return apply_op(fn, ins, name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction: str = "mean", pos_weight=None, name=None):
    logit = ensure_tensor(logit)
    label = ensure_tensor(label)
    ins = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        ins.append(ensure_tensor(weight))
    if has_pw:
        ins.append(ensure_tensor(pos_weight))

    def fn(z, y, *rest):
        i = 0
        w = rest[i] if has_w else None
        if has_w:
            i += 1
        pw = rest[i] if has_pw else None
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight on the y term
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return apply_op(fn, ins, name="bce_with_logits")


def nll_loss(input, label, weight=None, ignore_index: int = -100,
             reduction: str = "mean", name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    ins = [input, Tensor(label._value, stop_gradient=True)]
    has_w = weight is not None
    if has_w:
        ins.append(ensure_tensor(weight))

    def fn(logp, idx, *wt):
        idx = idx.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        if logp.ndim > 2:  # [N, C, d1..] -> move C last
            lp = jnp.moveaxis(logp, 1, -1)
        else:
            lp = logp
        picked = jnp.take_along_axis(lp, safe[..., None], axis=-1).squeeze(-1)
        loss = -jnp.where(valid, picked, 0.0)
        if has_w:
            w_per = jnp.where(valid, jnp.take(wt[0], safe), 0.0)
            loss = loss * w_per
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w_per), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)

    return apply_op(fn, ins, name="nll_loss")


def mse_loss(input, label, reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op(lambda a, b: _reduce((a - b) ** 2, reduction), [input, label],
                    name="mse_loss")


def square_error_cost(input, label):
    return mse_loss(input, label, reduction="none")


def l1_loss(input, label, reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction), [input, label],
                    name="l1_loss")


def smooth_l1_loss(input, label, reduction: str = "mean", delta: float = 1.0, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(a, b):
        d = a - b
        absd = jnp.abs(d)
        loss = jnp.where(absd < delta, 0.5 * d * d / delta, absd - 0.5 * delta)
        return _reduce(loss, reduction)

    return apply_op(fn, [input, label], name="smooth_l1_loss")


def kl_div(input, label, reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(logp, y):
        loss = jnp.where(y > 0, y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply_op(fn, [input, label], name="kl_div")


def margin_ranking_loss(input, other, label, margin: float = 0.0,
                        reduction: str = "mean", name=None):
    input, other, label = ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0.0), reduction),
        [input, other, label], name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin: float = 1.0,
                         reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op(
        lambda a, y: _reduce(
            jnp.where(y == 1, a, jnp.maximum(margin - a, 0.0)), reduction
        ),
        [input, label], name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin: float = 0.0,
                          reduction: str = "mean", name=None):
    input1, input2, label = ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)

    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)

    return apply_op(fn, [input1, input2, label], name="cosine_embedding_loss")


def log_loss(input, label, epsilon: float = 1e-4, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        [input, label], name="log_loss",
    )


def sigmoid_focal_loss(logit, label, normalizer=None, alpha: float = 0.25,
                       gamma: float = 2.0, reduction: str = "sum", name=None):
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    ins = [logit, label]
    has_n = normalizer is not None
    if has_n:
        ins.append(ensure_tensor(normalizer))

    def fn(z, y, *nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if has_n:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)

    return apply_op(fn, ins, name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon: float = 1e-5, name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(y1, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply_op(fn, [input, Tensor(label._value, stop_gradient=True)], name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg: float = 0.002):
    anchor, positive, labels = ensure_tensor(anchor), ensure_tensor(positive), ensure_tensor(labels)

    def fn(a, p, y):
        batch = a.shape[0]
        sim = a @ p.T
        y = y.reshape(-1)
        tgt = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.sum(tgt * logp, axis=1).mean()
        reg = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / (2 * batch)
        return xent + reg

    return apply_op(fn, [anchor, positive, Tensor(labels._value, stop_gradient=True)],
                    name="npair_loss")


def triplet_margin_loss(input, positive, negative, margin: float = 1.0, p: float = 2.0,
                        epsilon: float = 1e-6, swap: bool = False,
                        reduction: str = "mean", name=None):
    input, positive, negative = map(ensure_tensor, (input, positive, negative))

    def fn(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p + epsilon, axis=-1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p + epsilon, axis=-1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p + epsilon, axis=-1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(fn, [input, positive, negative], name="triplet_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None,
                                      margin: float = 1.0, swap: bool = False,
                                      reduction: str = "mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        from ...ops import minimum

        dn = minimum(dn, distance_function(positive, negative))
    dp, dn = ensure_tensor(dp), ensure_tensor(dn)
    return apply_op(
        lambda a, b: _reduce(jnp.maximum(a - b + margin, 0.0), reduction),
        [dp, dn], name="triplet_margin_with_distance_loss",
    )


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    ins = [input, label]
    has_w = weight is not None
    if has_w:
        ins.append(ensure_tensor(weight))

    def fn(z, y, *wt):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if has_w:
            loss = loss * wt[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)

    return apply_op(fn, ins, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op(
        lambda z, y: _reduce(jnp.log1p(jnp.exp(-y * z)), reduction),
        [input, label], name="soft_margin_loss",
    )


def poisson_nll_loss(input, label, log_input: bool = True, full: bool = False,
                     epsilon: float = 1e-8, reduction: str = "mean", name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)

    def fn(z, y):
        if log_input:
            loss = jnp.exp(z) - y * z
        else:
            loss = z - y * jnp.log(z + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op(fn, [input, label], name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full: bool = False,
                      epsilon: float = 1e-6, reduction: str = "mean", name=None):
    input, label, variance = map(ensure_tensor, (input, label, variance))

    def fn(mu, y, var):
        var = jnp.clip(var, epsilon, None)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, mu.dtype))
        return _reduce(loss, reduction)

    return apply_op(fn, [input, label, variance], name="gaussian_nll_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank: int = 0,
             reduction: str = "mean", norm_by_times: bool = False):
    """CTC via the standard alpha-recursion in log space, vectorized with
    lax.scan over time (reference: functional/loss.py ctc_loss → warpctc).
    log_probs: [T, N, C] (paddle layout); labels: [N, S] padded."""
    log_probs = ensure_tensor(log_probs)
    labels = ensure_tensor(labels)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def fn(lp, lbl, in_len, lbl_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, N, C = lp.shape
        S = lbl.shape[1]
        # extended label sequence with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((N, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lbl = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1).squeeze(1)
        alpha0 = alpha0.at[:, 1].set(first_lbl)
        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze alphas past each sequence's input length
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha_T, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
        # loss = -log(alpha[last_blank] + alpha[last_label])
        last = 2 * lbl_len.astype(jnp.int32)  # index of final blank
        aN = jnp.take_along_axis(alpha_T, last[:, None], axis=1).squeeze(1)
        aN1 = jnp.take_along_axis(
            alpha_T, jnp.maximum(last - 1, 0)[:, None], axis=1
        ).squeeze(1)
        ll = jnp.logaddexp(aN, jnp.where(lbl_len > 0, aN1, neg_inf))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply_op(
        fn,
        [log_probs, Tensor(labels._value, stop_gradient=True),
         Tensor(input_lengths._value, stop_gradient=True),
         Tensor(label_lengths._value, stop_gradient=True)],
        name="ctc_loss",
    )
