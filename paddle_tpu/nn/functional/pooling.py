"""Pooling functionals.

reference parity: python/paddle/nn/functional/pooling.py (phi pool kernels).
All windows ride ``lax.reduce_window`` — the XLA-native pooling primitive that
tiles onto the TPU vector unit; adaptive pools compute static per-output
windows (shapes are static under jit, so this unrolls into fused slices).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ...ops._apply import ensure_tensor, unary
from ...autograd.engine import apply_op

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d",
    "max_pool1d", "max_pool2d", "max_pool3d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
]


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pool_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad pool padding {padding}")


def _window(a_ndim, ksize, stride, n, channel_last):
    if channel_last:
        dims = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + ksize
        strides = (1, 1) + stride
    return dims, strides


def _full_pad(pad, n, channel_last):
    if isinstance(pad, str):
        return pad
    if channel_last:
        return [(0, 0)] + list(pad) + [(0, 0)]
    return [(0, 0), (0, 0)] + list(pad)


def _max_pool(x, kernel_size, stride, padding, ceil_mode, n, data_format, return_mask=False):
    x = ensure_tensor(x)
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    ksize = _tuplize(kernel_size, n)
    stride = _tuplize(stride if stride is not None else kernel_size, n)
    pad = _pool_pad(padding, n)

    def fn(a):
        dims, strides = _window(a.ndim, ksize, stride, n, channel_last)
        p = _full_pad(pad, n, channel_last)
        if isinstance(p, str):
            pcfg = p
        else:
            pcfg = p
            if ceil_mode:
                pcfg = [list(q) for q in pcfg]
                sp_axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
                for i, ax in enumerate(sp_axes):
                    size = a.shape[ax] + pcfg[ax][0] + pcfg[ax][1]
                    rem = (size - ksize[i]) % stride[i]
                    if rem:
                        pcfg[ax][1] += stride[i] - rem
                pcfg = [tuple(q) for q in pcfg]
        # floats MUST use -inf: jax only recognizes the differentiable
        # reduce_window_max monoid for (-inf, lax.max); finfo.min falls back
        # to the generic reduce_window which has no autodiff rule
        neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
               else jnp.iinfo(a.dtype).min)
        return lax.reduce_window(a, neg, lax.max, dims, strides, pcfg)

    out = unary(fn, x, name=f"max_pool{n}d")
    if return_mask:
        # flat-spatial argmax per window (paddle mask semantics): extract the
        # k-offset shifted views, stack, argmax over offsets, then map the
        # winning offset back to a global flat index. Exact — no packing tricks.
        if channel_last or isinstance(pad, str):
            raise NotImplementedError(
                "return_mask needs NC-first layout and explicit padding")

        def idx_fn(a):
            sp = a.shape[2:]
            neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) \
                else jnp.iinfo(a.dtype).min
            pcfg = [(0, 0), (0, 0)] + list(pad)
            # ceil_mode extension
            if ceil_mode:
                pcfg = [list(q) for q in pcfg]
                for i in range(n):
                    size = sp[i] + pcfg[2 + i][0] + pcfg[2 + i][1]
                    rem = (size - ksize[i]) % stride[i]
                    if rem:
                        pcfg[2 + i][1] += stride[i] - rem
                pcfg = [tuple(q) for q in pcfg]
            ap = jnp.pad(a, pcfg, constant_values=neg)
            out_sp = tuple(
                (ap.shape[2 + i] - ksize[i]) // stride[i] + 1 for i in range(n)
            )
            # global (padded) coordinates of each input element
            coords = jnp.meshgrid(*[jnp.arange(s) for s in ap.shape[2:]],
                                  indexing="ij")
            views, view_coords = [], []
            import itertools as _it

            for offs in _it.product(*[range(k) for k in ksize]):
                sl = tuple(
                    slice(offs[i], offs[i] + out_sp[i] * stride[i], stride[i])
                    for i in range(n)
                )
                views.append(ap[(slice(None), slice(None)) + sl])
                # flat UNPADDED spatial index of this element
                flat = jnp.zeros(out_sp, jnp.int32)
                mult = 1
                for i in reversed(range(n)):
                    c = coords[i][sl] - pad[i][0]
                    flat = flat + c.astype(jnp.int32) * mult
                    mult *= sp[i]
                view_coords.append(flat)
            stacked = jnp.stack(views, axis=2)  # [N, C, K, *out_sp]
            win = jnp.argmax(stacked, axis=2)  # [N, C, *out_sp]
            idx_stack = jnp.stack(view_coords, axis=0)  # [K, *out_sp]
            return jnp.take_along_axis(
                jnp.broadcast_to(idx_stack[None, None], stacked.shape),
                win[:, :, None], axis=2,
            ).squeeze(2)

        mask = unary(idx_fn, x, differentiable=False, name="max_pool_mask")
        return out, mask
    return out


def _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive, divisor_override,
              n, data_format):
    x = ensure_tensor(x)
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    ksize = _tuplize(kernel_size, n)
    stride = _tuplize(stride if stride is not None else kernel_size, n)
    pad = _pool_pad(padding, n)

    def fn(a):
        dims, strides = _window(a.ndim, ksize, stride, n, channel_last)
        p = _full_pad(pad, n, channel_last)
        if ceil_mode and not isinstance(p, str):
            p = [list(q) for q in p]
            sp_axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
            for i, ax in enumerate(sp_axes):
                size = a.shape[ax] + p[ax][0] + p[ax][1]
                rem = (size - ksize[i]) % stride[i]
                if rem:
                    p[ax][1] += stride[i] - rem
            p = [tuple(q) for q in p]
        summed = lax.reduce_window(a, 0.0, lax.add, dims, strides, p)
        if divisor_override:
            return summed / divisor_override
        if exclusive and not isinstance(p, str):
            ones = jnp.ones_like(a)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, p)
            return summed / jnp.maximum(counts, 1.0)
        return summed / float(np.prod(ksize))

    return unary(fn, x, name=f"avg_pool{n}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 1, df, return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 2, data_format, return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, ceil_mode, 3, data_format, return_mask)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive, None, 1, df)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, 2, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _avg_pool(x, kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, 3, data_format)


def _adaptive_windows(in_size, out_size):
    """Per-output [start, end) windows (paddle adaptive pooling semantics)."""
    starts = [(i * in_size) // out_size for i in range(out_size)]
    ends = [-(-((i + 1) * in_size) // out_size) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, data_format, op):
    x = ensure_tensor(x)
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    out_sp = _tuplize(output_size, n)
    out_sp = tuple(
        (x.shape[1 + i] if channel_last else x.shape[2 + i]) if o is None else o
        for i, o in enumerate(out_sp)
    )

    def fn(a):
        sp_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out = a
        for i, ax in enumerate(sp_axes):
            in_size = out.shape[ax]
            starts, ends = _adaptive_windows(in_size, out_sp[i])
            slices = []
            for s, e in zip(starts, ends):
                window = lax.slice_in_dim(out, s, e, axis=ax)
                if op == "avg":
                    slices.append(jnp.mean(window, axis=ax, keepdims=True))
                else:
                    slices.append(jnp.max(window, axis=ax, keepdims=True))
            out = jnp.concatenate(slices, axis=ax)
        return out

    return unary(fn, x, name=f"adaptive_{op}_pool{n}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def _adaptive_max_mask(x, output_size, n):
    """Flat-spatial argmax index per adaptive window (paddle mask semantics).
    Static per-cell windows → unrolled gathers, fused by XLA."""
    import itertools as _it

    out_sp = _tuplize(output_size, n)

    def fn(a):
        sp = a.shape[2:]
        windows = [_adaptive_windows(sp[i], out_sp[i]) for i in range(n)]
        cells = []
        for cell in _it.product(*[range(o) for o in out_sp]):
            sl = tuple(slice(windows[i][0][cell[i]], windows[i][1][cell[i]])
                       for i in range(n))
            w = a[(slice(None), slice(None)) + sl]
            flat = w.reshape(w.shape[0], w.shape[1], -1)
            loc = jnp.argmax(flat, axis=-1)
            # local flat → coords → global flat index
            wsp = w.shape[2:]
            rem = loc
            mult_g = 1
            gidx = jnp.zeros_like(loc)
            for i in reversed(range(n)):
                c = rem % wsp[i]
                rem = rem // wsp[i]
                gidx = gidx + (c + windows[i][0][cell[i]]) * mult_g
                mult_g *= sp[i]
            cells.append(gidx)
        stacked = jnp.stack(cells, axis=-1)  # [N, C, prod(out_sp)]
        return stacked.reshape(a.shape[:2] + out_sp).astype(jnp.int32)

    return unary(fn, ensure_tensor(x), differentiable=False, name="adaptive_max_mask")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, "NCW", "max")
    return (out, _adaptive_max_mask(x, output_size, 1)) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, "NCHW", "max")
    return (out, _adaptive_max_mask(x, output_size, 2)) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, "NCDHW", "max")
    return (out, _adaptive_max_mask(x, output_size, 3)) if return_mask else out


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, n, data_format):
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    ksize = _tuplize(kernel_size, n)
    stride_ = _tuplize(stride if stride is not None else kernel_size, n)
    if output_size is None:
        in_sp = x.shape[2:]
        out_sp = tuple(
            (in_sp[i] - 1) * stride_[i] + ksize[i] - 2 * _tuplize(padding, n)[i]
            for i in range(n)
        )
    else:
        out_sp = tuple(int(s) for s in output_size)[-n:]

    def fn(a, idx):
        nb, c = a.shape[0], a.shape[1]
        flat_sp = int(np.prod(out_sp))
        out = jnp.zeros((nb, c, flat_sp), a.dtype)
        flat_in = a.reshape(nb, c, -1)
        flat_idx = idx.reshape(nb, c, -1).astype(jnp.int32)
        bidx = jnp.arange(nb)[:, None, None]
        cidx = jnp.arange(c)[None, :, None]
        out = out.at[bidx, cidx, flat_idx].set(flat_in)
        return out.reshape((nb, c) + out_sp)

    return apply_op(fn, [x, indices], name=f"max_unpool{n}d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 1, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 2, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 3, data_format)
