"""Common functionals: linear, dropout, embedding, padding, interpolate…

reference parity: python/paddle/nn/functional/common.py + input.py
(one_hot/embedding) + vision.py (pixel_shuffle). The TPU notes that matter:
``linear`` is a plain jnp.dot so XLA maps it straight onto the MXU; ``dropout``
consumes a threefry key from the global generator so it is deterministic and
jit-capturable; padding/resize are lax ops with static attrs.
"""
from __future__ import annotations

import math
import numbers
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...generator import default_generator
from ...ops._apply import ensure_tensor, unary
from ...tensor import Tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "one_hot", "pad", "zeropad2d", "interpolate", "upsample", "bilinear",
    "cosine_similarity", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "unfold", "fold", "label_smooth", "class_center_sample", "normalize",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Paddle weight layout [in, out]
    (reference: nn/functional/common.py linear → phi matmul+add)."""
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    if bias is None:
        return apply_op(lambda a, w: jnp.matmul(a, w), [x, weight], name="linear")
    bias = ensure_tensor(bias)
    return apply_op(lambda a, w, b: jnp.matmul(a, w) + b, [x, weight, bias], name="linear")


def dropout(x, p: float = 0.5, axis=None, training: bool = True,
            mode: str = "upscale_in_train", name=None):
    """reference: nn/functional/common.py dropout (phi dropout kernel).
    Threefry key is consumed eagerly so repeated calls differ."""
    if isinstance(p, Tensor):
        p = float(p.item())
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return unary(lambda a: a * (1.0 - p), x, name="dropout_infer")
        x = ensure_tensor(x)
        return x
    if p == 1.0:
        return unary(lambda a: jnp.zeros_like(a), x, name="dropout")
    key = default_generator.next_key()

    def fn(a):
        if axis is None:
            mask_shape = a.shape
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            mask_shape = tuple(
                a.shape[i] if i in axes else 1 for i in range(a.ndim)
            )
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return unary(fn, x, name="dropout")


def dropout2d(x, p: float = 0.5, training: bool = True,
              data_format: str = "NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p: float = 0.5, training: bool = True,
              data_format: str = "NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p: float = 0.5, training: bool = True, name=None):
    """SELU-preserving dropout (reference: common.py alpha_dropout)."""
    if not training or p == 0.0:
        return ensure_tensor(x)
    alpha = 1.6732632423543772848170429916717
    scale = 1.0507009873554804934193349852946
    alpha_p = -alpha * scale
    a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    key = default_generator.next_key()

    def fn(arr):
        keep = jax.random.bernoulli(key, 1.0 - p, arr.shape)
        return (a * jnp.where(keep, arr, alpha_p) + b).astype(arr.dtype)

    return unary(fn, x, name="alpha_dropout")


def embedding(x, weight, padding_idx: Optional[int] = None,
              sparse: bool = False, name=None):
    """Gather rows of weight (reference: functional/input.py embedding →
    phi embedding kernel). padding_idx rows get zero gradient by zeroing the
    row in the lookup table inside the differentiated fn."""
    del sparse  # no SelectedRows on TPU; dense grads (XLA scatter-add)
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    def fn(ids, w):
        if padding_idx is not None:
            pidx = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            w = w.at[pidx].set(0.0)
        return jnp.take(w, ids.astype(jnp.int32), axis=0)

    x_only = Tensor(x._value, stop_gradient=True)
    return apply_op(fn, [x_only, weight], name="embedding")


def one_hot(x, num_classes: int, name=None):
    x = ensure_tensor(x)
    return apply_op(
        lambda ids: jax.nn.one_hot(ids.astype(jnp.int32), num_classes, dtype=jnp.float32),
        [Tensor(x._value, stop_gradient=True)], name="one_hot",
    )


def _norm_pad(pad, ndim, data_format):
    """Convert paddle pad spec (per-dim low/high, innermost-first) to
    jnp.pad config."""
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = list(pad)
    cfg = [(0, 0)] * ndim
    # paddle: pad applies to the last len(pad)//2 spatial dims, ordered from
    # the innermost spatial dim outward when NCHW: [l, r, t, b] pads W then H
    spatial_axes = list(range(2, ndim)) if data_format.startswith("NC") else list(range(1, ndim - 1))
    n = len(pad) // 2
    axes = spatial_axes[::-1][:n]
    for i, ax in enumerate(axes):
        cfg[ax] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    return cfg


def pad(x, pad, mode: str = "constant", value: float = 0.0,
        data_format: str = "NCHW", name=None):
    """reference: nn/functional/common.py pad (phi pad3d kernel)."""
    x = ensure_tensor(x)
    ndim = x.ndim
    if isinstance(pad, (list, tuple)) and len(pad) == 2 * ndim:
        # full-tensor pad spec, innermost-dim-first pairs
        cfg = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(ndim)]
    else:
        cfg = _norm_pad(pad, ndim, data_format)
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def fn(a):
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)

    return unary(fn, x, name="pad")


def zeropad2d(x, padding, data_format: str = "NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def _resize_nearest(a, out_hw, data_format):
    if data_format == "NCHW":
        n, c, h, w = a.shape
        oh, ow = out_hw
        rows = (jnp.arange(oh) * (h / oh)).astype(jnp.int32)
        cols = (jnp.arange(ow) * (w / ow)).astype(jnp.int32)
        return a[:, :, rows][:, :, :, cols]
    n, h, w, c = a.shape
    oh, ow = out_hw
    rows = (jnp.arange(oh) * (h / oh)).astype(jnp.int32)
    cols = (jnp.arange(ow) * (w / ow)).astype(jnp.int32)
    return a[:, rows][:, :, cols]


def interpolate(x, size=None, scale_factor=None, mode: str = "nearest",
                align_corners: bool = False, align_mode: int = 0,
                data_format: str = "NCHW", name=None):
    """reference: nn/functional/common.py interpolate (phi interp kernels).
    bilinear/bicubic/trilinear ride jax.image.resize; nearest is an index
    gather (matches paddle's floor-sampling when align_corners=False)."""
    x = ensure_tensor(x)
    nd = x.ndim
    if data_format.startswith("NC"):
        spatial = x.shape[2:]
        channel_last = False
    else:
        spatial = x.shape[1:-1]
        channel_last = True
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy().reshape(-1)]
        out_spatial = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]
    else:
        if isinstance(scale_factor, (numbers.Number,)):
            scale_factor = [scale_factor] * len(spatial)
        out_spatial = [int(math.floor(s * f)) for s, f in zip(spatial, scale_factor)]

    if mode == "nearest" and nd == 4 and not align_corners:
        return unary(lambda a: _resize_nearest(a, out_spatial, data_format), x,
                     name="interp_nearest")

    method = {"nearest": "nearest", "bilinear": "bilinear", "bicubic": "bicubic",
              "trilinear": "trilinear", "linear": "linear", "area": "linear"}[mode]
    if method == "trilinear":
        method = "linear"

    def fn(a):
        if channel_last:
            out_shape = (a.shape[0],) + tuple(out_spatial) + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + tuple(out_spatial)
        if align_corners and method in ("linear", "bilinear", "bicubic"):
            # jax.image.resize has no align_corners; emulate via
            # scale_and_translate: want in_coord = out_coord * (in-1)/(out-1),
            # while the kernel maps in j -> out j*scale + translation with
            # half-pixel centers — solving gives translation = 0.5*(1-scale).
            in_spatial = spatial
            scale = [
                (o - 1) / (i - 1) if i > 1 and o > 1 else 1.0
                for i, o in zip(in_spatial, out_spatial)
            ]
            trans = [0.5 * (1.0 - s) for s in scale]
            sdims = list(range(2, nd)) if not channel_last else list(range(1, nd - 1))
            return jax.image.scale_and_translate(
                a, out_shape, sdims,
                jnp.array(scale, jnp.float32),
                jnp.array(trans, jnp.float32),
                method="bilinear" if method != "bicubic" else "bicubic",
            ).astype(a.dtype)
        return jax.image.resize(a, out_shape, method=method).astype(a.dtype)

    return unary(fn, x, name=f"interp_{mode}")


def upsample(x, size=None, scale_factor=None, mode: str = "nearest",
             align_corners: bool = False, align_mode: int = 0,
             data_format: str = "NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Bilinear map y[b, o] = x1[b,:] W[o] x2[b,:]ᵀ (reference: common.py bilinear)."""
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def fn(a, b, w, *maybe_bias):
        y = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if maybe_bias:
            y = y + maybe_bias[0]
        return y

    ins = [x1, x2, weight] + ([ensure_tensor(bias)] if bias is not None else [])
    return apply_op(fn, ins, name="bilinear")


def cosine_similarity(x1, x2, axis: int = 1, eps: float = 1e-8, name=None):
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply_op(fn, [x1, x2], name="cosine_similarity")


def normalize(x, p: float = 2, axis: int = 1, epsilon: float = 1e-12, name=None):
    return unary(
        lambda a: a / jnp.maximum(
            jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p), epsilon
        ),
        x, name="normalize",
    )


def pixel_shuffle(x, upscale_factor: int, data_format: str = "NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return unary(fn, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor: int, data_format: str = "NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return unary(fn, x, name="pixel_unshuffle")


def channel_shuffle(x, groups: int, data_format: str = "NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return unary(fn, x, name="channel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: common.py unfold → phi unfold kernel)."""
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    if isinstance(paddings, int):
        pd = [paddings] * 4
    elif len(paddings) == 2:
        pd = [paddings[0], paddings[1], paddings[0], paddings[1]]
    else:
        pd = list(paddings)

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = a[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return unary(fn, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im — adjoint of unfold (reference: common.py fold)."""
    os_ = [output_sizes] * 2 if isinstance(output_sizes, int) else list(output_sizes)
    ks = [kernel_sizes] * 2 if isinstance(kernel_sizes, int) else list(kernel_sizes)
    st = [strides] * 2 if isinstance(strides, int) else list(strides)
    dl = [dilations] * 2 if isinstance(dilations, int) else list(dilations)
    if isinstance(paddings, int):
        pd = [paddings] * 4
    elif len(paddings) == 2:
        pd = [paddings[0], paddings[1], paddings[0], paddings[1]]
    else:
        pd = list(paddings)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[2], os_[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        cols = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(cols[:, :, i, j])
        return out[:, :, pd[0]: ph - pd[2], pd[1]: pw - pd[3]]

    return unary(fn, x, name="fold")


def label_smooth(label, prior_dist=None, epsilon: float = 0.1, name=None):
    label = ensure_tensor(label)
    if prior_dist is not None:
        prior_dist = ensure_tensor(prior_dist)
        return apply_op(
            lambda l, p: (1 - epsilon) * l + epsilon * p.reshape((1,) * (l.ndim - 1) + (-1,)),
            [label, prior_dist], name="label_smooth",
        )
    return unary(lambda l: (1 - epsilon) * l + epsilon / l.shape[-1], label,
                 name="label_smooth")


def class_center_sample(label, num_classes: int, num_samples: int, group=None):
    """reference: common.py class_center_sample (PartialFC sampling)."""
    label = ensure_tensor(label)
    lbl = label._value
    pos = jnp.unique(lbl, size=min(num_classes, int(lbl.size)), fill_value=-1)
    pos = pos[pos >= 0]
    n_pos = int(pos.size)
    if n_pos >= num_samples:
        sampled = pos[:num_samples]
    else:
        key = default_generator.next_key()
        all_ids = jnp.arange(num_classes)
        mask = jnp.isin(all_ids, pos, invert=True)
        neg = all_ids[mask]
        perm = jax.random.permutation(key, neg.shape[0])
        sampled = jnp.concatenate([pos, neg[perm[: num_samples - n_pos]]])
    sampled = jnp.sort(sampled)
    remap = jnp.searchsorted(sampled, lbl)
    return Tensor(remap), Tensor(sampled)
