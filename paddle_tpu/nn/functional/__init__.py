"""paddle_tpu.nn.functional — the F namespace.

reference parity: python/paddle/nn/functional/__init__.py.
"""
from .activation import *  # noqa: F401,F403
from .extended import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403

from . import activation, attention, common, conv, extended, loss, norm, pooling

__all__ = (
    activation.__all__ + attention.__all__ + common.__all__ + conv.__all__
    + extended.__all__ + loss.__all__ + norm.__all__ + pooling.__all__
)
