"""Activation functionals.

reference parity: python/paddle/nn/functional/activation.py backed by phi
activation kernels (paddle/phi/kernels/activation_kernel.cc). Each is one pure
jax.nn/jnp expression routed through the autograd tape; XLA fuses them into
neighbouring matmuls on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._apply import unary

__all__ = [
    "celu", "elu", "gelu", "glu", "gumbel_softmax", "hardshrink", "hardsigmoid",
    "hardswish", "hardtanh", "leaky_relu", "log_sigmoid", "log_softmax",
    "maxout", "mish", "prelu", "relu", "relu_", "relu6", "rrelu", "selu",
    "sigmoid", "silu", "softmax", "softmax_", "softplus", "softshrink",
    "softsign", "swish", "tanh", "tanh_", "tanhshrink", "thresholded_relu",
]


def relu(x, name=None):
    return unary(jax.nn.relu, x, name="relu")


def relu_(x, name=None):
    from ...autograd.engine import inplace_rebind

    return inplace_rebind(x, relu(x))


def relu6(x, name=None):
    return unary(jax.nn.relu6, x, name="relu6")


def sigmoid(x, name=None):
    return unary(jax.nn.sigmoid, x, name="sigmoid")


def log_sigmoid(x, name=None):
    return unary(jax.nn.log_sigmoid, x, name="log_sigmoid")


def tanh(x, name=None):
    return unary(jnp.tanh, x, name="tanh")


def tanh_(x, name=None):
    from ...autograd.engine import inplace_rebind

    return inplace_rebind(x, tanh(x))


def tanhshrink(x, name=None):
    return unary(lambda a: a - jnp.tanh(a), x, name="tanhshrink")


def gelu(x, approximate: bool = False, name=None):
    return unary(lambda a: jax.nn.gelu(a, approximate=approximate), x, name="gelu")


def silu(x, name=None):
    return unary(jax.nn.silu, x, name="silu")


def swish(x, name=None):
    return unary(jax.nn.silu, x, name="swish")


def mish(x, name=None):
    return unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x, name="mish")


def elu(x, alpha: float = 1.0, name=None):
    return unary(lambda a: jax.nn.elu(a, alpha=alpha), x, name="elu")


def celu(x, alpha: float = 1.0, name=None):
    return unary(lambda a: jax.nn.celu(a, alpha=alpha), x, name="celu")


def selu(
    x,
    scale: float = 1.0507009873554804934193349852946,
    alpha: float = 1.6732632423543772848170429916717,
    name=None,
):
    return unary(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, name="selu"
    )


def leaky_relu(x, negative_slope: float = 0.01, name=None):
    return unary(lambda a: jax.nn.leaky_relu(a, negative_slope=negative_slope),
                 x, name="leaky_relu")


def prelu(x, weight, data_format: str = "NCHW", name=None):
    from ...ops._apply import ensure_tensor
    from ...autograd.engine import apply_op

    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    def fn(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape = [1] * a.ndim
            shape[axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a > 0, a, wb * a)

    return apply_op(fn, [x, weight], name="prelu")


def rrelu(x, lower: float = 1.0 / 8.0, upper: float = 1.0 / 3.0,
          training: bool = False, name=None):
    if training:
        from ...generator import default_generator

        key = default_generator.next_key()

        def fn(a):
            slopes = jax.random.uniform(key, a.shape, a.dtype, minval=lower, maxval=upper)
            return jnp.where(a >= 0, a, slopes * a)

        return unary(fn, x, name="rrelu")
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def hardtanh(x, min: float = -1.0, max: float = 1.0, name=None):
    return unary(lambda a: jnp.clip(a, min, max), x, name="hardtanh")


def hardshrink(x, threshold: float = 0.5, name=None):
    return unary(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype),
                 x, name="hardshrink")


def softshrink(x, threshold: float = 0.5, name=None):
    return unary(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)).astype(a.dtype),
        x, name="softshrink",
    )


def hardsigmoid(x, slope: float = 0.1666667, offset: float = 0.5, name=None):
    return unary(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x, name="hardsigmoid")


def hardswish(x, name=None):
    return unary(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, name="hardswish")


def softplus(x, beta: float = 1.0, threshold: float = 20.0, name=None):
    return unary(
        lambda a: jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta),
        x, name="softplus",
    )


def softsign(x, name=None):
    return unary(jax.nn.soft_sign, x, name="softsign")


def thresholded_relu(x, threshold: float = 1.0, value: float = 0.0, name=None):
    return unary(lambda a: jnp.where(a > threshold, a, value).astype(a.dtype),
                 x, name="thresholded_relu")


def softmax(x, axis: int = -1, dtype=None, name=None):
    from ... import dtypes

    dt = dtypes.convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.softmax(a, axis=axis)

    return unary(fn, x, name="softmax")


def softmax_(x, axis: int = -1, dtype=None, name=None):
    from ...autograd.engine import inplace_rebind

    return inplace_rebind(x, softmax(x, axis, dtype))


def log_softmax(x, axis: int = -1, dtype=None, name=None):
    from ... import dtypes

    dt = dtypes.convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.log_softmax(a, axis=axis)

    return unary(fn, x, name="log_softmax")


def glu(x, axis: int = -1, name=None):
    return unary(lambda a: jax.nn.glu(a, axis=axis), x, name="glu")


def maxout(x, groups: int, axis: int = 1, name=None):
    def fn(a):
        ax = axis if axis >= 0 else a.ndim + axis
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return unary(fn, x, name="maxout")


def gumbel_softmax(x, temperature: float = 1.0, hard: bool = False,
                   axis: int = -1, name=None):
    from ...generator import default_generator

    key = default_generator.next_key()

    def fn(a):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, a.shape, jnp.float32, minval=1e-20, maxval=1.0)
        )).astype(a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(
                jnp.argmax(y, axis=axis), y.shape[axis], dtype=y.dtype, axis=axis
            )
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return unary(fn, x, name="gumbel_softmax")
