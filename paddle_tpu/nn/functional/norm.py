"""Normalization functionals.

reference parity: python/paddle/nn/functional/norm.py (phi batch_norm /
layer_norm / instance_norm / group_norm kernels). On TPU these are pure
jnp reductions — XLA fuses them with surrounding elementwise work; no cudnn
BN path is needed. Running-stat mutation happens in the Layer (layer/norm.py),
keeping these functionals pure.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...ops._apply import ensure_tensor
from ...tensor import Tensor

__all__ = [
    "batch_norm", "layer_norm", "instance_norm", "group_norm", "local_response_norm",
]


def _stat_axes(ndim, data_format):
    if data_format.startswith("NC"):
        return tuple(i for i in range(ndim) if i != 1), 1
    return tuple(range(ndim - 1)), ndim - 1


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9, epsilon: float = 1e-5,
               data_format: str = "NCHW", use_global_stats: Optional[bool] = None,
               name=None):
    """Pure functional BN. In training mode, updates running stats IN-PLACE on
    the passed tensors (reference semantics: phi batch_norm kernel writes
    mean_out/variance_out). Stat update is done under stop_gradient."""
    x = ensure_tensor(x)
    running_mean = ensure_tensor(running_mean)
    running_var = ensure_tensor(running_var)
    axes, ch_axis = _stat_axes(x.ndim, data_format)
    use_batch_stats = training and not use_global_stats

    def shape_for(v, nd):
        s = [1] * nd
        s[ch_axis] = -1
        return v.reshape(s)

    if use_batch_stats:
        xv = x._value
        mean = jnp.mean(xv, axis=axes)
        var = jnp.var(xv, axis=axes)
        # update running stats (host-side mutation; recorded by jit tracer)
        m = momentum
        running_mean._set_value((m * running_mean._value + (1 - m) * mean).astype(running_mean._value.dtype))
        running_var._set_value((m * running_var._value + (1 - m) * var).astype(running_var._value.dtype))
        mean_t, var_t = Tensor(mean), Tensor(var)
    else:
        mean_t, var_t = running_mean, running_var

    ins = [x, mean_t, var_t]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(ensure_tensor(weight))
    if has_b:
        ins.append(ensure_tensor(bias))

    def fn(a, mu, v2, *wb):
        nd = a.ndim
        mu_ = shape_for(jnp.asarray(mu), nd)
        v_ = shape_for(jnp.asarray(v2), nd)
        y = (a - mu_) * jnp.asarray(1.0 / jnp.sqrt(v_ + epsilon), a.dtype)
        i = 0
        if has_w:
            y = y * shape_for(wb[i], nd)
            i += 1
        if has_b:
            y = y + shape_for(wb[i], nd)
        return y.astype(a.dtype)

    # mean/var used for normalization must participate in autograd when they
    # came from the batch (paddle semantics): recompute them inside fn instead
    if use_batch_stats:
        ins2 = [x] + ins[3:]

        def fn_train(a, *wb):
            mu = jnp.mean(a, axis=axes, keepdims=True)
            v2 = jnp.var(a, axis=axes, keepdims=True)
            y = (a - mu) / jnp.sqrt(v2 + epsilon)
            i = 0
            if has_w:
                y = y * shape_for(wb[i], a.ndim)
                i += 1
            if has_b:
                y = y + shape_for(wb[i], a.ndim)
            return y.astype(a.dtype)

        return apply_op(fn_train, ins2, name="batch_norm")
    return apply_op(fn, ins, name="batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None,
               epsilon: float = 1e-5, name=None):
    """reference: functional/norm.py layer_norm (phi layer_norm kernel)."""
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    ins = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(ensure_tensor(weight))
    if has_b:
        ins.append(ensure_tensor(bias))

    def fn(a, *wb):
        mu = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        y = (a - mu) / jnp.sqrt(var + epsilon)
        i = 0
        if has_w:
            y = y * wb[i]
            i += 1
        if has_b:
            y = y + wb[i]
        return y.astype(a.dtype)

    return apply_op(fn, ins, name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats: bool = True, momentum: float = 0.9,
                  eps: float = 1e-5, data_format: str = "NCHW", name=None):
    x = ensure_tensor(x)
    # stats per (N, C) over spatial dims
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sp_axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else tuple(range(1, x.ndim - 1))
    ins = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(ensure_tensor(weight))
    if has_b:
        ins.append(ensure_tensor(bias))

    def fn(a, *wb):
        mu = jnp.mean(a, axis=sp_axes, keepdims=True)
        var = jnp.var(a, axis=sp_axes, keepdims=True)
        y = (a - mu) / jnp.sqrt(var + eps)
        shape = [1] * a.ndim
        shape[ch_axis] = -1
        i = 0
        if has_w:
            y = y * wb[i].reshape(shape)
            i += 1
        if has_b:
            y = y + wb[i].reshape(shape)
        return y.astype(a.dtype)

    return apply_op(fn, ins, name="instance_norm")


def group_norm(x, num_groups: int, epsilon: float = 1e-5, weight=None, bias=None,
               data_format: str = "NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = not data_format.startswith("NC")
    ins = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(ensure_tensor(weight))
    if has_b:
        ins.append(ensure_tensor(bias))

    def fn(a, *wb):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        rest = a_t.shape[2:]
        g = a_t.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, g.ndim))
        mu = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        y = ((g - mu) / jnp.sqrt(var + epsilon)).reshape(a_t.shape)
        shape = [1, c] + [1] * (a_t.ndim - 2)
        i = 0
        if has_w:
            y = y * wb[i].reshape(shape)
            i += 1
        if has_b:
            y = y + wb[i].reshape(shape)
        if channel_last:
            y = jnp.moveaxis(y, 1, -1)
        return y.astype(a.dtype)

    return apply_op(fn, ins, name="group_norm")


def local_response_norm(x, size: int, alpha: float = 1e-4, beta: float = 0.75,
                        k: float = 1.0, data_format: str = "NCHW", name=None):
    x = ensure_tensor(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1

    def fn(a):
        sq = a * a
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            sl = [slice(None)] * a.ndim
            sl[ch_axis] = slice(i, i + a.shape[ch_axis])
            acc = acc + padded[tuple(sl)]
        return a / ((k + alpha * acc) ** beta)

    from ...ops._apply import unary

    return unary(fn, x, name="local_response_norm")
