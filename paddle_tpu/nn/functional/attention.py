"""Attention functionals.

reference parity: FlashAttention integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu:213, dynload/flashattn.h) and
nn.functional.scaled_dot_product_attention. On TPU the fused kernel is a
Pallas flash-attention (paddle_tpu/ops/pallas/flash_attention.py) used when
running on TPU hardware; elsewhere (CPU tests) the reference jnp einsum path
runs — same math, XLA-fused.
"""
from __future__ import annotations

import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from ...autograd.engine import apply_op
from ...ops._apply import ensure_tensor

__all__ = ["scaled_dot_product_attention", "flash_attention", "flash_attn_unpadded"]


def _sdpa_ref(q, k, v, mask, dropout_p, causal, scale, key=None):
    """[B, S, H, D] paddle flash-attn layout."""
    qh = jnp.swapaxes(q, 1, 2)  # B H S D
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # B S H D


# FLAGS_use_pallas_flash_attention (framework/flags.py) — lets users route
# attention off the Pallas kernel for debugging/numerics comparison
pallas_flash_enabled = True

# Measured dispatch threshold (v5e, r4, tools/bench_flash.py with chained
# data-dependent timing): the Pallas kernel wins fwd+bwd at EVERY swept
# length — S=512: 1.93 vs 1.99ms, S=1024: 1.73 vs 5.07ms, S=2048: 3.71 vs
# 11.11ms, S=4096: 6.09 vs 32.57ms (naive attention is HBM-bound on the
# [S,S] score tensor; flash never materializes it). r2's "XLA wins at
# S=1024" was an artifact of per-call wall timing that the axon tunnel's
# async dispatch made meaningless. Below 512 the [S,S] block is small
# enough that XLA's fusion ties and dispatch overhead dominates.
# Env override lets the bench ladder A/B the threshold without code edits.
pallas_flash_min_seq = int(os.environ.get("PADDLE_TPU_FLASH_MIN_SEQ", 512))


def _use_pallas(q_value, seq_len: int) -> bool:
    if not pallas_flash_enabled or seq_len < pallas_flash_min_seq:
        return False
    try:
        if isinstance(q_value, jax.core.Tracer):
            # inside a jit trace there is no concrete device; the trace
            # compiles for the default backend (this is the hot path —
            # every StaticFunction train step traces through here).
            # Caveat: a jit targeting a NON-default backend on a TPU host
            # will still stage the TPU kernel; route off via
            # incubate.set_config({"kernel": {"enable": False}}) there.
            return jax.default_backend() == "tpu"
        dev = list(q_value.devices())[0]
        return dev.platform == "tpu"
    except Exception:
        return False


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p: float = 0.0, is_causal: bool = False,
                                 training: bool = True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] (paddle flash-attn layout)."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    drop = dropout_p if training else 0.0
    rng_key = None
    if drop > 0.0:
        from ...generator import default_generator

        rng_key = default_generator.next_key()

    seq_len = int(query.shape[1]) if len(query.shape) >= 2 else 0

    def _as_key_padding(mask, batch):
        """ONLY the unambiguous [B, 1, 1, Sk] BOOL form (True = attend)
        → [B, Sk] keep array; anything else returns None and stays on
        the XLA path. 2D/3D bool masks are NOT accepted: under XLA's
        trailing-dim broadcast a [Sq, Sk] or [B/H-aligned, Sk] mask means
        per-query/per-head masking, which is not key padding — routing
        them to the kernel would silently change semantics per device.
        The batch dim must match exactly (a broadcast [1,1,1,Sk] with
        B>1 would under-fill the kernel's [B·H] grid)."""
        if mask is None or mask.dtype != jnp.bool_:
            return None
        shp = tuple(int(x) for x in mask.shape)
        if (len(shp) == 4 and shp[0] == batch and shp[1] == 1
                and shp[2] == 1 and shp[3] == klen):
            # klen must match exactly: a stale-length mask would be
            # silently truncated/mis-padded by the kernel but fail
            # loudly on the XLA broadcast — keep both paths failing the
            # same way
            return mask.reshape(shp[0], shp[3])
        return None

    mask_val = ensure_tensor(attn_mask)._value if attn_mask is not None \
        else None
    klen = int(key.shape[1]) if len(key.shape) >= 2 else 0
    kpad = _as_key_padding(mask_val, int(query.shape[0]))
    if ((attn_mask is None or kpad is not None)
            and _use_pallas(query._value, seq_len)):
        from ...ops.pallas import flash_attention as fa

        # dropout runs INSIDE the kernel (counter-based hash mask — no
        # [S,S] mask materialization; the naive path's u32 bernoulli draw
        # is 512MB/layer at B8 S1024 H16). The seed is derived from the
        # framework RNG key as DATA — under StaticFunction tracing the key
        # is traced state, so a host int would be a TracerArrayConversion
        # error (and a retrace per step even if it weren't).
        from ...tensor import Tensor

        ins = [query, key, value]
        has_seed = drop > 0.0
        if has_seed:
            seed_val = jax.random.randint(
                rng_key, (), 0, 1 << 24).astype(jnp.float32)
            ins.append(Tensor(seed_val, stop_gradient=True))
        has_kpad = kpad is not None
        if has_kpad:
            ins.append(Tensor(kpad, stop_gradient=True))

        def fn(q, k, v, *extra, _p=drop, _hs=has_seed, _hk=has_kpad):
            seed = extra[0] if _hs else 0
            kp = extra[-1] if _hk else None
            return fa.flash_attention_bshd(
                q, k, v, causal=is_causal, dropout_p=_p,
                dropout_seed=seed, key_padding_mask=kp)

        return apply_op(fn, ins, name="flash_attention")

    ins = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        ins.append(ensure_tensor(attn_mask))

    def fn(q, k, v, *m):
        mask = m[0] if has_mask else None
        return _sdpa_ref(q, k, v, mask, drop, is_causal, None, rng_key)

    return apply_op(fn, ins, name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout: float = 0.0, causal: bool = False,
                    return_softmax: bool = False, fixed_seed_offset=None,
                    rng_name: str = "", training: bool = True, name=None):
    """reference: paddle.nn.functional.flash_attention.flash_attention
    (phi flash_attn kernel). Returns (out, softmax_lse placeholder)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal, training)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale: float = None,
                        dropout: float = 0.0, causal: bool = False,
                        return_softmax: bool = False, training: bool = True, name=None):
    """Varlen flash attention (reference: flash_attn_unpadded). Implemented by
    segment-masked dense attention: tokens are packed [total, H, D] and
    cu_seqlens delimit sequences."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    cu_q = ensure_tensor(cu_seqlens_q)

    def fn(q, k, v, cu):
        total, h, d = q.shape
        seg = jnp.cumsum(
            jnp.zeros((total,), jnp.int32).at[cu[1:-1]].add(1)
        )  # segment id per token
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("qhd,khd->hqk", q, k) * s
        same = seg[:, None] == seg[None, :]
        if causal:
            same = same & (jnp.arange(total)[:, None] >= jnp.arange(total)[None, :])
        logits = jnp.where(same[None], logits, -1e30)
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, v)

    from ...tensor import Tensor

    out = apply_op(fn, [query, key, value, Tensor(cu_q._value, stop_gradient=True)],
                   name="flash_attn_unpadded")
    return out, None
