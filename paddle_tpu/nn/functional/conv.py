"""Convolution functionals.

reference parity: python/paddle/nn/functional/conv.py (phi conv kernels,
paddle/phi/kernels/conv_kernel.h). On TPU every conv is one
``lax.conv_general_dilated`` — XLA tiles it onto the MXU directly; there is no
algo search (the reference's cudnn exhaustive-search/autotune machinery,
phi/kernels/autotune/, is unnecessary here).

Paddle layout conventions: input NCHW (default), weight [out_c, in_c/groups, *k].
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ...autograd.engine import apply_op
from ...ops._apply import ensure_tensor

__all__ = [
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
]


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _norm_padding(padding, n, strides, dilations, ksize, in_spatial):
    """Paddle padding spec → lax padding list [(lo, hi)] * n or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return [(0, 0)] * n
        if p == "SAME":
            # XLA SAME semantics match paddle's SAME (pad evenly, extra at end)
            return "SAME"
        raise ValueError(f"unknown padding {padding}")
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            # [[lo, hi], ...] possibly including batch/channel dims
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        # [lo0, hi0, lo1, hi1, ...] paddle order per spatial dim
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    if len(padding) == n + 2 and isinstance(padding[0], (list, tuple)):
        return [tuple(p) for p in padding[2:]]
    raise ValueError(f"bad padding spec {padding}")


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n, name):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    lhs_spec, rhs_spec, out_spec = _dim_numbers(n, channel_last)
    ksize = weight.shape[2:]
    pad = _norm_padding(padding, n, stride, dilation, ksize, None)

    def fn(a, w, *mb):
        # weight is paddle [out, in/g, *k] = OIHW; lax wants per rhs_spec
        if channel_last and n == 2:
            w = jnp.transpose(w, (2, 3, 1, 0))
        elif channel_last and n == 1:
            w = jnp.transpose(w, (2, 1, 0))
        elif channel_last and n == 3:
            w = jnp.transpose(w, (2, 3, 4, 1, 0))
        out = lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec if not channel_last else rhs_spec, out_spec),
            preferred_element_type=None,
        )
        if mb:
            b = mb[0]
            if channel_last:
                out = out + b.reshape((1,) * (out.ndim - 1) + (-1,))
            else:
                out = out + b.reshape((1, -1) + (1,) * n)
        return out

    ins = [x, weight]
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return apply_op(fn, ins, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format: str = "NCL", name=None):
    df = "NWC" if data_format in ("NLC",) else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, df, 1, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format: str = "NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format: str = "NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups,
                    dilation, data_format, output_size, n, name):
    """Transposed conv via gradient-of-conv (lax.conv_transpose matches paddle
    semantics with transpose_kernel for OIHW weights [in, out/g, *k])."""
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    opad = _tuplize(output_padding, n) if output_padding != 0 or isinstance(output_padding, (list, tuple)) else (0,) * n
    lhs_spec, rhs_spec, out_spec = _dim_numbers(n, channel_last)
    ksize = weight.shape[2:]
    pad = _norm_padding(padding, n, stride, dilation, ksize, None)

    def fn(a, w, *mb):
        # paddle conv_transpose weight layout: [in_c, out_c/groups, *k]
        # implement as input-dilated conv with flipped kernel
        if isinstance(pad, str):
            pads = None  # SAME handled below
        else:
            pads = pad
        k_eff = [dilation[i] * (ksize[i] - 1) + 1 for i in range(n)]
        if pads is None:
            in_sp = a.shape[2:] if not channel_last else a.shape[1:-1]
            out_sp = [s * stride[i] for i, s in enumerate(in_sp)]
            tot = [max(k_eff[i] - stride[i], 0) for i in range(n)]
            pads = [(tot[i] // 2, tot[i] - tot[i] // 2) for i in range(n)]
        extra = [0] * n
        if output_size is not None:
            # output_size acts as an output_padding: extend the high side so
            # the transposed conv COMPUTES the extra rows (paddle semantics),
            # rather than zero-padding them after the fact
            target = [int(s) for s in (
                output_size if isinstance(output_size, (list, tuple))
                else [output_size] * n)]
            in_sp = a.shape[2:] if not channel_last else a.shape[1:-1]
            for i in range(n):
                natural = ((in_sp[i] - 1) * stride[i] + k_eff[i]
                           - pads[i][0] - pads[i][1] + opad[i])
                extra[i] = target[i] - natural
                if extra[i] < 0 or extra[i] >= stride[i] + dilation[i]:
                    raise ValueError(
                        f"invalid output_size {target[i]} for dim {i}: natural "
                        f"size is {natural}")
        lo_hi = [
            (k_eff[i] - 1 - pads[i][0], k_eff[i] - 1 - pads[i][1] + opad[i] + extra[i])
            for i in range(n)
        ]
        # flip spatial dims of kernel, swap in/out channels
        wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            # paddle layout [in, out/g, *k] → lax layout [out, in/g, *k]
            g = groups
            wf = wf.reshape((g, w.shape[0] // g) + w.shape[1:])  # [g, in/g, out/g, *k]
            wf = jnp.swapaxes(wf, 1, 2)  # [g, out/g, in/g, *k]
            wf = wf.reshape((w.shape[1] * g, w.shape[0] // g) + w.shape[2:])
        else:
            wf = jnp.swapaxes(wf, 0, 1)  # [out, in, *k]
        if channel_last:
            if n == 1:
                wf = jnp.transpose(wf, (2, 1, 0))
            elif n == 2:
                wf = jnp.transpose(wf, (2, 3, 1, 0))
            else:
                wf = jnp.transpose(wf, (2, 3, 4, 1, 0))
        out = lax.conv_general_dilated(
            a, wf, window_strides=(1,) * n, padding=lo_hi,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
        )
        if mb:
            b = mb[0]
            if channel_last:
                out = out + b.reshape((1,) * (out.ndim - 1) + (-1,))
            else:
                out = out + b.reshape((1, -1) + (1,) * n)
        return out

    ins = [x, weight]
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return apply_op(fn, ins, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None,
                     data_format: str = "NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, df, output_size, 1, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None,
                     data_format: str = "NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, output_size, 2, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None,
                     data_format: str = "NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, output_size, 3, "conv3d_transpose")
