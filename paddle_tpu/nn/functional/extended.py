"""Functional API tail: distance/masking/vision-warp/decode ops.

Reference parity: the remaining ``python/paddle/nn/functional/__all__``
entries — pairwise_distance, diag_embed, sequence_mask (tensor/creation
in the reference, exported via functional), affine_grid + grid_sample
(vision warping), temporal_shift (TSM), gather_tree (beam-search
backtrace), margin_cross_entropy (ArcFace), hsigmoid_loss (hierarchical
softmax over the default complete binary tree), multi_margin_loss,
rnnt_loss (transducer forward algorithm via a diagonal-wavefront scan),
sparse_attention (block-CSR mask materialized densely — the TPU MXU
prefers the dense-masked matmul for the block sizes the reference
supports), elu_.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...autograd.engine import apply_op, inplace_rebind
from ...ops._apply import ensure_tensor

__all__ = [
    "pairwise_distance", "elu_", "diag_embed", "sequence_mask",
    "hsigmoid_loss", "margin_cross_entropy", "rnnt_loss", "affine_grid",
    "grid_sample", "gather_tree", "temporal_shift", "sparse_attention",
    "multi_margin_loss",
]


def pairwise_distance(x, y, p: float = 2.0, epsilon: float = 1e-6,
                      keepdim: bool = False, name=None):
    """p-norm of (x - y) along the last dim (reference:
    nn/functional/distance.py)."""
    return apply_op(
        lambda a, b: jnp.linalg.norm(a - b + epsilon, ord=p, axis=-1,
                                     keepdims=keepdim),
        [ensure_tensor(x), ensure_tensor(y)], name="pairwise_distance")


def elu_(x, alpha: float = 1.0, name=None):
    from .activation import elu

    x = ensure_tensor(x)
    out = elu(x, alpha)
    inplace_rebind(x, out)
    return x


def diag_embed(input, offset: int = 0, dim1: int = -2, dim2: int = -1,
               name=None):
    """Batch diagonal embedding (reference: tensor/creation diag_embed)."""
    t = ensure_tensor(input)

    def fn(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        out = base.at[..., rows, cols].set(v)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        order = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two new axes at dim1/dim2
        perm = []
        src = iter(order)
        for i in range(nd):
            if i == min(d1, d2):
                perm.append(nd - 2)
            elif i == max(d1, d2):
                perm.append(nd - 1)
            else:
                perm.append(next(src))
        return jnp.transpose(out, perm)

    return apply_op(fn, [t], name="diag_embed")


def sequence_mask(x, maxlen: Optional[int] = None, dtype="int64", name=None):
    """lengths → [*, maxlen] 0/1 mask (reference: sequence_mask op)."""
    from ... import dtypes

    t = ensure_tensor(x)
    if maxlen is None:
        maxlen = int(np.asarray(jax.device_get(t._value)).max())
    dt = dtypes.convert_dtype(dtype)

    def fn(lens):
        pos = jnp.arange(maxlen)
        return (pos[None, :] < lens[..., None].astype(jnp.int64)).astype(dt)

    return apply_op(fn, [t], name="sequence_mask")


# ------------------------------------------------------------ losses


def _hsigmoid_paths(num_classes: int):
    """Default complete-binary-tree paths: node ids and left/right codes
    per class (host-side, static given num_classes)."""
    depth = max(1, math.ceil(math.log2(max(num_classes, 2))))
    paths = np.zeros((num_classes, depth), np.int64)
    codes = np.zeros((num_classes, depth), np.float32)
    lengths = np.zeros((num_classes,), np.int64)
    for c in range(num_classes):
        # walk from the root of a complete binary tree with num_classes
        # leaves; internal nodes are numbered heap-style from 1
        node = c + num_classes  # leaf id in heap numbering
        path = []
        code = []
        while node > 1:
            parent = node // 2
            path.append(parent - 1)  # internal nodes 0-based
            code.append(float(node % 2))  # right child → 1
            node = parent
        path.reverse()
        code.reverse()
        lengths[c] = len(path)
        paths[c, :len(path)] = path
        codes[c, :len(code)] = code
    return paths, codes, lengths


def hsigmoid_loss(input, label, num_classes: int, weight, bias=None,
                  path_table=None, path_code=None, is_sparse: bool = False,
                  name=None):
    """Hierarchical sigmoid loss (reference: nn/functional/loss.py
    hsigmoid_loss; the default tree matches the reference's complete
    binary tree over num_classes leaves)."""
    x = ensure_tensor(input)
    y = ensure_tensor(label)
    w = ensure_tensor(weight)
    ins = [x, y, w]
    has_bias = bias is not None
    if has_bias:
        ins.append(ensure_tensor(bias))
    if path_table is None:
        paths_np, codes_np, lens_np = _hsigmoid_paths(num_classes)
    else:
        paths_np = np.asarray(jax.device_get(ensure_tensor(path_table)._value))
        codes_np = np.asarray(jax.device_get(ensure_tensor(path_code)._value))
        lens_np = (paths_np >= 0).sum(axis=-1)

    def fn(xv, yv, wv, *rest):
        bv = rest[0] if has_bias else None
        paths = jnp.asarray(paths_np)
        codes = jnp.asarray(codes_np)
        lens = jnp.asarray(lens_np)
        yl = yv.reshape(-1).astype(jnp.int64)
        p = paths[yl]            # [B, D] node ids
        c = codes[yl]            # [B, D] 0/1
        ln = lens[yl]            # [B]
        d = jnp.arange(p.shape[1])[None, :]
        valid = d < ln[:, None]
        wn = wv[p]               # [B, D, F]
        logits = jnp.einsum("bdf,bf->bd", wn, xv)
        if bv is not None:
            logits = logits + bv.reshape(-1)[p]
        # binary CE per internal node: -log σ((1-2c)·logit)
        per_node = jax.nn.softplus(logits) - c * logits
        loss = jnp.where(valid, per_node, 0.0).sum(axis=1)
        return loss[:, None]

    return apply_op(fn, ins, name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1: float = 1.0,
                         margin2: float = 0.5, margin3: float = 0.0,
                         scale: float = 64.0, group=None,
                         return_softmax: bool = False,
                         reduction: Optional[str] = None, name=None):
    """ArcFace-family margin softmax (reference: margin_cross_entropy op):
    target logit cosθ → cos(m1·θ + m2) − m3, then scaled CE."""
    lg = ensure_tensor(logits)
    y = ensure_tensor(label)

    def fn(lv, yv):
        yl = yv.reshape(-1).astype(jnp.int64)
        cos_t = jnp.clip(jnp.take_along_axis(lv, yl[:, None], axis=1),
                         -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        adjusted = jnp.cos(margin1 * theta + margin2) - margin3
        one_hot = jax.nn.one_hot(yl, lv.shape[-1], dtype=lv.dtype)
        out = (lv * (1 - one_hot) + adjusted * one_hot) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.take_along_axis(logp, yl[:, None], axis=1)
        if reduction == "mean":
            loss = loss.mean()
        elif reduction == "sum":
            loss = loss.sum()
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return apply_op(fn, [lg, y], name="margin_cross_entropy")


def multi_margin_loss(input, label, p: int = 1, margin: float = 1.0,
                      weight=None, reduction: str = "mean", name=None):
    """Multiclass hinge (reference: nn/functional/loss.py
    multi_margin_loss)."""
    x = ensure_tensor(input)
    y = ensure_tensor(label)
    ins = [x, y]
    if weight is not None:
        ins.append(ensure_tensor(weight))

    def fn(xv, yv, *rest):
        yl = yv.reshape(-1).astype(jnp.int64)
        target = jnp.take_along_axis(xv, yl[:, None], axis=1)
        hinge = jnp.maximum(0.0, margin - target + xv) ** p
        if rest:
            hinge = hinge * rest[0].reshape(-1)[yl][:, None]
        one_hot = jax.nn.one_hot(yl, xv.shape[-1], dtype=xv.dtype)
        loss = (hinge * (1 - one_hot)).sum(axis=1) / xv.shape[-1]
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply_op(fn, ins, name="multi_margin_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank: int = 0,
              fastemit_lambda: float = 0.0, reduction: str = "mean",
              name=None):
    if fastemit_lambda:
        # the FastEmit-regularized objective is a different loss, not a
        # scaling of this one — refusing beats silently ignoring the knob
        raise NotImplementedError(
            "fastemit_lambda != 0 is not implemented; the plain transducer "
            "NLL is (use fastemit_lambda=0)")
    """RNN-Transducer loss (reference: warprnnt integration,
    nn/functional/loss.py rnnt_loss). Forward algorithm in log space:
    α[t,u] = logaddexp(α[t−1,u] + blank(t−1,u), α[t,u−1] + emit(t,u−1)),
    computed as a scan over t with an inner scan over u — compiles to a
    static program, grads via autodiff (no custom backward needed)."""
    acts = ensure_tensor(input)          # [B, T, U+1, V] log-probs or logits
    labels = ensure_tensor(label)        # [B, U]
    in_lens = ensure_tensor(input_lengths)
    lab_lens = ensure_tensor(label_lengths)

    def fn(a, lab, tl, ul):
        a = jax.nn.log_softmax(a.astype(jnp.float32), axis=-1)
        B, T, U1, V = a.shape
        U = U1 - 1
        lab = lab.astype(jnp.int64)
        blank_lp = a[..., blank]                     # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            a[:, :, :U, :], lab[:, None, :, None].repeat(T, 1), axis=3
        )[..., 0]                                    # [B, T, U]
        neg = jnp.float32(-1e30)

        def t_step(alpha_prev, t):
            # alpha_prev: [B, U+1] = α[t-1, ·]; compute α[t, ·]
            from_blank = alpha_prev + blank_lp[:, t - 1, :]

            def u_step(carry, u):
                # carry = α[t, u-1]
                val = jnp.where(
                    u == 0, from_blank[:, 0],
                    jnp.logaddexp(from_blank[:, u],
                                  carry + emit_lp[:, t, u - 1]))
                return val, val

            _, cols = jax.lax.scan(u_step, jnp.full((B,), neg, jnp.float32),
                                   jnp.arange(U1))
            alpha_t = jnp.moveaxis(cols, 0, 1)  # [B, U+1]
            return alpha_t, alpha_t

        # α[0, u]: only emits along u at t=0
        def u0_step(carry, u):
            val = jnp.where(u == 0, jnp.zeros((B,), jnp.float32),
                            carry + emit_lp[:, 0, u - 1])
            return val, val

        _, cols0 = jax.lax.scan(u0_step, jnp.full((B,), neg, jnp.float32),
                                jnp.arange(U1))
        alpha0 = jnp.moveaxis(cols0, 0, 1)
        _, alphas = jax.lax.scan(t_step, alpha0, jnp.arange(1, T))
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]
        alphas = jnp.moveaxis(alphas, 1, 0)                       # [B, T, U+1]

        t_idx = (tl.reshape(-1) - 1).astype(jnp.int64)
        u_idx = ul.reshape(-1).astype(jnp.int64)
        final = alphas[jnp.arange(B), t_idx, u_idx]
        last_blank = blank_lp[jnp.arange(B), t_idx, u_idx]
        nll = -(final + last_blank)
        if reduction == "mean":
            return nll.mean()
        if reduction == "sum":
            return nll.sum()
        return nll

    return apply_op(fn, [acts, labels, in_lens, lab_lens], name="rnnt_loss")


# ------------------------------------------------------- vision warping


def affine_grid(theta, out_shape, align_corners: bool = True, name=None):
    """Sampling grid from batched affine matrices (reference:
    nn/functional/vision.py affine_grid). theta [N,2,3] → grid [N,H,W,2]."""
    th = ensure_tensor(theta)
    if hasattr(out_shape, "numpy"):
        out_shape = [int(s) for s in np.asarray(out_shape.numpy())]
    N, C, H, W = [int(s) for s in out_shape]

    def fn(tv):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [HW, 3]
        out = jnp.einsum("nij,pj->npi", tv.astype(jnp.float32), base)
        return out.reshape(tv.shape[0], H, W, 2)

    return apply_op(fn, [th], name="affine_grid")


def grid_sample(x, grid, mode: str = "bilinear",
                padding_mode: str = "zeros", align_corners: bool = True,
                name=None):
    """Sample NCHW input at normalized grid locations (reference:
    nn/functional/vision.py grid_sample)."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError("mode must be 'bilinear' or 'nearest'")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError("bad padding_mode")

    def fn(xv, gv):
        N, C, H, W = xv.shape
        gx = gv[..., 0]
        gy = gv[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def sample(ix, iy):
            inb = ((ix >= 0) & (ix <= W - 1) & (iy >= 0)
                   & (iy <= H - 1))
            if padding_mode == "border":
                ixc = jnp.clip(ix, 0, W - 1)
                iyc = jnp.clip(iy, 0, H - 1)
                inb = jnp.ones_like(inb)
            elif padding_mode == "reflection":
                span_x = max(W - 1, 1)
                span_y = max(H - 1, 1)
                ixc = jnp.abs(jnp.mod(ix + span_x * 2, span_x * 2) - span_x)
                iyc = jnp.abs(jnp.mod(iy + span_y * 2, span_y * 2) - span_y)
                ixc = jnp.clip(ixc, 0, W - 1)
                iyc = jnp.clip(iyc, 0, H - 1)
                inb = jnp.ones_like(inb)
            else:
                ixc = jnp.clip(ix, 0, W - 1)
                iyc = jnp.clip(iy, 0, H - 1)
            vals = xv[jnp.arange(N)[:, None, None], :,
                      iyc.astype(jnp.int32), ixc.astype(jnp.int32)]
            # vals: [N, Hg, Wg, C] → mask out-of-bounds for zeros mode
            return vals * inb[..., None].astype(xv.dtype)

        if mode == "nearest":
            out = sample(jnp.round(fx), jnp.round(fy))
        else:
            x0 = jnp.floor(fx)
            y0 = jnp.floor(fy)
            x1, y1 = x0 + 1, y0 + 1
            wa = ((x1 - fx) * (y1 - fy))[..., None]
            wb = ((x1 - fx) * (fy - y0))[..., None]
            wc = ((fx - x0) * (y1 - fy))[..., None]
            wd = ((fx - x0) * (fy - y0))[..., None]
            out = (sample(x0, y0) * wa + sample(x0, y1) * wb
                   + sample(x1, y0) * wc + sample(x1, y1) * wd)
        return jnp.moveaxis(out, -1, 1)  # [N, C, Hg, Wg]

    return apply_op(fn, [ensure_tensor(x), ensure_tensor(grid)],
                    name="grid_sample")


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25,
                   data_format: str = "NCHW", name=None):
    """TSM channel shift along time (reference: temporal_shift op)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError("bad data_format")

    def fn(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        NT, C, H, W = v.shape
        N = NT // seg_num
        fold = int(C * shift_ratio)
        r = v.reshape(N, seg_num, C, H, W)
        back = jnp.concatenate(
            [r[:, 1:, :fold], jnp.zeros_like(r[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(r[:, :1, fold:2 * fold]),
             r[:, :-1, fold:2 * fold]], axis=1)
        keep = r[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_op(fn, [ensure_tensor(x)], name="temporal_shift")


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (reference: gather_tree op): walk parent
    pointers from the last step back, yielding the full sequences."""

    def fn(idv, pv):
        T = idv.shape[0]

        def step(beam_idx, t):
            tok = jnp.take_along_axis(idv[t], beam_idx, axis=-1)
            parent = jnp.take_along_axis(pv[t], beam_idx, axis=-1)
            return parent, tok

        init = jnp.broadcast_to(
            jnp.arange(idv.shape[-1])[None, :], idv.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply_op(fn, [ensure_tensor(ids), ensure_tensor(parents)],
                    name="gather_tree")


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention (reference: sparse_attention op, GPU-only
    there). The CSR pattern is materialized as a dense boolean mask —
    on TPU the masked dense matmul IS the fast path for the pattern
    sizes the reference supports."""
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    off = ensure_tensor(sparse_csr_offset)
    cols = ensure_tensor(sparse_csr_columns)

    def fn(qv, kv, vv, offv, colv):
        B, H, S, D = qv.shape
        scores = jnp.einsum("bhsd,bhtd->bhst", qv, kv) / math.sqrt(D)
        # CSR → dense mask [B, H, S, S]: entry i belongs to the row whose
        # offset range contains i
        pos = jnp.arange(colv.shape[-1])

        def one_head(offr, colr):
            rows = jnp.searchsorted(offr, pos, side="right") - 1
            m = jnp.zeros((S, S), bool).at[rows, colr].set(True)
            return m

        mask = jax.vmap(jax.vmap(one_head))(offv, colv)
        scores = jnp.where(mask, scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhst,bhtd->bhsd", p, vv)

    return apply_op(fn, [q, k, v, off, cols], name="sparse_attention")
