"""vision.ops tail — detection ops completing the reference surface.

Reference parity: ``python/paddle/vision/ops.py`` — yolo_loss, prior_box,
matrix_nms, psroi_pool/PSRoIPool, distribute_fpn_proposals,
generate_proposals, read_file, decode_jpeg. Detection post-processing
is host-orchestrated the way the reference's CPU kernels are; the
per-box math is jnp.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply_op
from ..ops._apply import ensure_tensor
from ..tensor import Tensor

__all__ = ["yolo_loss", "prior_box", "matrix_nms", "psroi_pool", "PSRoIPool",
           "distribute_fpn_proposals", "generate_proposals", "read_file",
           "decode_jpeg"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: vision/ops.py yolo_loss → yolov3_loss op):
    coordinate MSE/BCE + objectness/class BCE with per-anchor target
    assignment by best-IoU; predictions above ignore_thresh with no
    matched target are excluded from the noobj term."""
    xt = ensure_tensor(x)
    gb = ensure_tensor(gt_box)
    gl = ensure_tensor(gt_label)
    ins = [xt, gb, gl]
    if gt_score is not None:
        ins.append(ensure_tensor(gt_score))
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    na = len(mask)

    def fn(xv, boxes, labels, *rest):
        scores = rest[0] if rest else None
        B, C, H, W = xv.shape
        xv = xv.reshape(B, na, 5 + class_num, H, W)
        px = jax.nn.sigmoid(xv[:, :, 0]) * scale_x_y \
            - (scale_x_y - 1) / 2          # [B,na,H,W]
        py = jax.nn.sigmoid(xv[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        pw = xv[:, :, 2]
        ph = xv[:, :, 3]
        pobj = xv[:, :, 4]
        pcls = xv[:, :, 5:]                 # [B,na,cls,H,W]

        img_size = float(downsample_ratio * H)
        anchors_all = jnp.asarray(an)       # [A,2]
        anchors_used = anchors_all[jnp.asarray(mask)]  # [na,2]

        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        bx = (px + gx) / W                  # normalized center
        by = (py + gy) / H
        bw = jnp.exp(pw) * anchors_used[None, :, 0, None, None] / img_size
        bh = jnp.exp(ph) * anchors_used[None, :, 1, None, None] / img_size

        # gt boxes [B,N,4] normalized xywh; label 0 padding rows have w==0
        gt_valid = boxes[..., 2] > 0        # [B,N]
        # best anchor per gt by wh-IoU against ALL anchors
        gw = boxes[..., 2] * img_size
        gh = boxes[..., 3] * img_size
        inter = (jnp.minimum(gw[..., None], anchors_all[None, None, :, 0])
                 * jnp.minimum(gh[..., None], anchors_all[None, None, :, 1]))
        union = (gw * gh)[..., None] + (anchors_all[:, 0]
                                        * anchors_all[:, 1])[None, None] - inter
        best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # [B,N]

        gi = jnp.clip((boxes[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((boxes[..., 1] * H).astype(jnp.int32), 0, H - 1)

        # build dense targets by scatter (padding rows scatter to cell 0 of
        # anchor best_anchor with weight 0 via gt_valid mask)
        def per_image(args):
            (pobj_i, pcls_i, px_i, py_i, pw_i, ph_i, boxes_i, labels_i,
             valid_i, ba_i, gi_i, gj_i, score_i) = args
            obj_t = jnp.zeros((na, H, W))
            tx = jnp.zeros((na, H, W))
            ty = jnp.zeros((na, H, W))
            tw = jnp.zeros((na, H, W))
            th = jnp.zeros((na, H, W))
            tcls = jnp.zeros((na, class_num, H, W))
            wgt = jnp.zeros((na, H, W))
            mask_arr = jnp.asarray(mask)
            # anchor index within this level (-1 → not this level)
            ai = jnp.argmax(ba_i[:, None] == mask_arr[None, :], axis=1)
            on_level = (ba_i[:, None] == mask_arr[None, :]).any(axis=1)
            w_ok = valid_i & on_level
            wvals = jnp.where(w_ok, score_i, 0.0)
            obj_t = obj_t.at[ai, gj_i, gi_i].max(wvals)
            wgt = wgt.at[ai, gj_i, gi_i].max(
                jnp.where(w_ok, 2.0 - boxes_i[:, 2] * boxes_i[:, 3], 0.0))
            tx = tx.at[ai, gj_i, gi_i].add(
                jnp.where(w_ok, boxes_i[:, 0] * W - gi_i, 0.0))
            ty = ty.at[ai, gj_i, gi_i].add(
                jnp.where(w_ok, boxes_i[:, 1] * H - gj_i, 0.0))
            anchor_wh = anchors_all[ba_i]
            tw = tw.at[ai, gj_i, gi_i].add(jnp.where(
                w_ok, jnp.log(jnp.maximum(
                    boxes_i[:, 2] * img_size / anchor_wh[:, 0], 1e-9)), 0.0))
            th = th.at[ai, gj_i, gi_i].add(jnp.where(
                w_ok, jnp.log(jnp.maximum(
                    boxes_i[:, 3] * img_size / anchor_wh[:, 1], 1e-9)), 0.0))
            smooth = (1.0 / class_num if use_label_smooth and class_num > 1
                      else 0.0)
            onehot = jax.nn.one_hot(labels_i.reshape(-1), class_num)
            onehot = onehot * (1.0 - smooth) + smooth / class_num
            tcls = tcls.at[ai, :, gj_i, gi_i].add(
                jnp.where(w_ok[:, None], onehot, 0.0))
            return obj_t, tx, ty, tw, th, tcls, wgt

        score_in = (scores if scores is not None
                    else jnp.ones(boxes.shape[:2]))
        obj_t, tx, ty, tw, th, tcls, wgt = jax.vmap(per_image)(
            (pobj, pcls, px, py, pw, ph, boxes, labels, gt_valid,
             best_anchor, gi, gj, score_in))

        bce = lambda lg, tgt: jax.nn.softplus(lg) - tgt * lg
        pos = obj_t > 0
        loss_xy = (wgt * (bce(xv[:, :, 0], tx) + bce(xv[:, :, 1], ty))
                   * pos).sum((1, 2, 3))
        loss_wh = (wgt * ((pw - tw) ** 2 + (ph - th) ** 2)
                   * pos * 0.5).sum((1, 2, 3))
        # ignore mask: predicted boxes with IoU>thresh against any gt
        pb = jnp.stack([bx, by, bw, bh], -1).reshape(B, -1, 4)

        def iou_pred_gt(pred, gt, valid):
            px1 = pred[:, 0] - pred[:, 2] / 2
            py1 = pred[:, 1] - pred[:, 3] / 2
            px2 = pred[:, 0] + pred[:, 2] / 2
            py2 = pred[:, 1] + pred[:, 3] / 2
            gx1 = gt[:, 0] - gt[:, 2] / 2
            gy1 = gt[:, 1] - gt[:, 3] / 2
            gx2 = gt[:, 0] + gt[:, 2] / 2
            gy2 = gt[:, 1] + gt[:, 3] / 2
            iw = jnp.maximum(jnp.minimum(px2[:, None], gx2[None])
                             - jnp.maximum(px1[:, None], gx1[None]), 0)
            ih = jnp.maximum(jnp.minimum(py2[:, None], gy2[None])
                             - jnp.maximum(py1[:, None], gy1[None]), 0)
            inter = iw * ih
            uni = ((px2 - px1) * (py2 - py1))[:, None] \
                + ((gx2 - gx1) * (gy2 - gy1))[None] - inter
            iou = inter / jnp.maximum(uni, 1e-9)
            return (iou * valid[None]).max(axis=1)

        best_iou = jax.vmap(iou_pred_gt)(pb, boxes, gt_valid)
        ignore = (best_iou > ignore_thresh).reshape(B, na, H, W)
        noobj = (~pos) & (~ignore)
        loss_obj = (bce(pobj, obj_t) * (pos | noobj)).sum((1, 2, 3))
        loss_cls = (bce(pcls, tcls) * pos[:, :, None]).sum((1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    return apply_op(fn, ins, name="yolo_loss")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference: vision/ops.py prior_box)."""
    inp = ensure_tensor(input)
    img = ensure_tensor(image)
    H, W = int(inp.shape[2]), int(inp.shape[3])
    img_h, img_w = int(img.shape[2]), int(img.shape[3])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H

    ratios = []
    for ar in aspect_ratios:
        ratios.append(ar)
        if flip and ar != 1.0:
            ratios.append(1.0 / ar)

    # prior order per min_size (reference prior_box kernel):
    #   False: min box, ratio boxes, max box
    #   True:  min box, max box, ratio boxes (SSD's trained-channel order)
    whs = []
    for i, s in enumerate(min_sizes):
        whs.append((s, s))
        max_wh = None
        if max_sizes:
            m = math.sqrt(s * max_sizes[i])
            max_wh = (m, m)
        ratio_whs = [(s * math.sqrt(ar), s / math.sqrt(ar))
                     for ar in ratios if ar != 1.0]
        if min_max_aspect_ratios_order:
            if max_wh:
                whs.append(max_wh)
            whs.extend(ratio_whs)
        else:
            whs.extend(ratio_whs)
            if max_wh:
                whs.append(max_wh)
    num_priors = len(whs)

    cx = (np.arange(W) + offset) * step_w
    cy = (np.arange(H) + offset) * step_h
    gx, gy = np.meshgrid(cx, cy)
    out = np.zeros((H, W, num_priors, 4), np.float32)
    for k, (bw, bh) in enumerate(whs):
        out[..., k, 0] = (gx - bw / 2) / img_w
        out[..., k, 1] = (gy - bh / 2) / img_h
        out[..., k, 2] = (gx + bw / 2) / img_w
        out[..., k, 3] = (gy + bh / 2) / img_h
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var)))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference: vision/ops.py matrix_nms — SOLOv2's soft
    suppression: each box's score decays by its max-IoU overlap with
    higher-scored boxes of the same class)."""
    bv = np.asarray(ensure_tensor(bboxes).numpy())    # [B, M, 4]
    sv = np.asarray(ensure_tensor(scores).numpy())    # [B, C, M]
    all_out, all_idx, rois_num = [], [], []
    B, C, M = sv.shape
    for b in range(B):
        outs, idxs = [], []
        for c in range(C):
            if c == background_label:
                continue
            sc = sv[b, c]
            keep = sc > score_threshold
            if not keep.any():
                continue
            cand = np.nonzero(keep)[0]
            order = cand[np.argsort(-sc[cand])][:nms_top_k]
            boxes = bv[b, order]
            s = sc[order]
            x1, y1, x2, y2 = boxes.T
            off = 0.0 if normalized else 1.0
            area = (x2 - x1 + off) * (y2 - y1 + off)
            iw = np.maximum(np.minimum(x2[:, None], x2[None])
                            - np.maximum(x1[:, None], x1[None]) + off, 0)
            ih = np.maximum(np.minimum(y2[:, None], y2[None])
                            - np.maximum(y1[:, None], y1[None]) + off, 0)
            inter = iw * ih
            iou = inter / np.maximum(area[:, None] + area[None] - inter,
                                     1e-9)
            iou = np.triu(iou, 1)  # iou[i, j] for suppressor i < candidate j
            # compensate_i: suppressor i's own max overlap with boxes
            # scored above IT (how suppressed the suppressor itself is)
            comp = iou.max(axis=0)                 # [n] column max
            if use_gaussian:
                decay_m = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                                 / gaussian_sigma)
            else:
                decay_m = (1 - iou) / np.maximum(1 - comp[:, None], 1e-9)
            # candidate j decays by its WORST suppressor (min over i<j);
            # rows i>=j carry iou=0 → decay 1/exp(+comp²)>=1, masked by min
            decay = np.minimum(decay_m, 1.0).min(axis=0)
            ds = s * decay
            ok = ds > post_threshold
            for i in np.nonzero(ok)[0]:
                outs.append([c, ds[i], *boxes[i]])
                idxs.append(b * M + order[i])
        outs = np.asarray(outs, np.float32).reshape(-1, 6)
        idxs = np.asarray(idxs, np.int64)
        if keep_top_k > 0 and len(outs) > keep_top_k:
            top = np.argsort(-outs[:, 1])[:keep_top_k]
            outs, idxs = outs[top], idxs[top]
        all_out.append(outs)
        all_idx.append(idxs)
        rois_num.append(len(outs))
    out = Tensor(jnp.asarray(np.concatenate(all_out)
                             if all_out else np.zeros((0, 6), np.float32)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(
            np.concatenate(all_idx) if all_idx else np.zeros(0, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(res) if len(res) > 1 else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference: vision/ops.py
    psroi_pool — R-FCN): channel group (i,j) pools from spatial bin
    (i,j) of the RoI."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    xt = ensure_tensor(x)
    bt = ensure_tensor(boxes)
    C = int(xt.shape[1])
    if C % (oh * ow):
        raise ValueError(f"channels {C} not divisible by output bins "
                         f"{oh}x{ow}")
    out_c = C // (oh * ow)

    def fn(xv, bx):
        n_boxes = bx.shape[0]

        def one(box):
            x1, y1, x2, y2 = box * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1) / oh
            rw = jnp.maximum(x2 - x1, 0.1) / ow
            H, W = xv.shape[2], xv.shape[3]
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)
            outs = []
            feat = xv[0]  # single-image assumption per reference boxes_num
            for i in range(oh):
                for j in range(ow):
                    y_lo = y1 + i * rh
                    y_hi = y1 + (i + 1) * rh
                    x_lo = x1 + j * rw
                    x_hi = x1 + (j + 1) * rw
                    my = ((ys >= jnp.floor(y_lo))
                          & (ys < jnp.ceil(y_hi))).astype(jnp.float32)
                    mx = ((xs >= jnp.floor(x_lo))
                          & (xs < jnp.ceil(x_hi))).astype(jnp.float32)
                    m = my[:, None] * mx[None, :]
                    denom = jnp.maximum(m.sum(), 1.0)
                    grp = feat[(i * ow + j) * out_c:(i * ow + j + 1) * out_c]
                    outs.append((grp * m[None]).sum((1, 2)) / denom)
            return jnp.stack(outs, 1).reshape(out_c, oh, ow)

        return jax.vmap(one)(bx)

    return apply_op(fn, [xt, bt], name="psroi_pool")


class PSRoIPool:
    """Layer form (reference: vision/ops.py PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference: vision/ops.py
    distribute_fpn_proposals): level = floor(refer + log2(sqrt(area)/
    refer_scale))."""
    rv = np.asarray(ensure_tensor(fpn_rois).numpy())
    off = 1.0 if pixel_offset else 0.0
    w = rv[:, 2] - rv[:, 0] + off
    h = rv[:, 3] - rv[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-9))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-9)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    # image id per roi from the per-image counts (rois are concatenated)
    if rois_num is not None:
        counts = np.asarray(ensure_tensor(rois_num).numpy()).astype(np.int64)
        img_of = np.repeat(np.arange(len(counts)), counts)
    else:
        counts = None
        img_of = np.zeros(len(rv), np.int64)
    multi_rois, order = [], []
    rois_num_per_level = [] if counts is not None else None
    for L in range(min_level, max_level + 1):
        on_level = lvl == L
        # within a level, keep image-major order so per-image counts are
        # contiguous (the reference's per-level LoD)
        idx = np.nonzero(on_level)[0]
        idx = idx[np.argsort(img_of[idx], kind="stable")]
        multi_rois.append(Tensor(jnp.asarray(rv[idx])))
        order.append(idx)
        if counts is not None:
            per_img = np.asarray(
                [int((img_of[idx] == b).sum()) for b in range(len(counts))],
                np.int32)
            rois_num_per_level.append(Tensor(jnp.asarray(per_img)))
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore_ind = np.empty_like(order)
    restore_ind[order] = np.arange(len(order))
    out = (multi_rois, Tensor(jnp.asarray(restore_ind[:, None])))
    if rois_num_per_level is not None:
        out = out + (rois_num_per_level,)
    return out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference: vision/ops.py
    generate_proposals): decode anchors with deltas, clip, filter small,
    NMS, top-k."""
    from .ops import nms as _nms

    sv = np.asarray(ensure_tensor(scores).numpy())        # [B, A, H, W]
    dv = np.asarray(ensure_tensor(bbox_deltas).numpy())   # [B, 4A, H, W]
    iv = np.asarray(ensure_tensor(img_size).numpy())      # [B, 2]
    av = np.asarray(ensure_tensor(anchors).numpy()).reshape(-1, 4)
    vv = np.asarray(ensure_tensor(variances).numpy()).reshape(-1, 4)
    B, A, H, W = sv.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_nums, all_scores = [], [], []
    for b in range(B):
        s = sv[b].transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = dv[b].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        anc = np.broadcast_to(av.reshape(1, 1, A, 4), (H, W, A, 4)
                              ).reshape(-1, 4) if av.shape[0] == A else av
        var = np.broadcast_to(vv.reshape(1, 1, A, 4), (H, W, A, 4)
                              ).reshape(-1, 4) if vv.shape[0] == A else vv
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        cx = var[:, 0] * d[:, 0] * aw + acx
        cy = var[:, 1] * d[:, 1] * ah + acy
        bw = aw * np.exp(np.minimum(var[:, 2] * d[:, 2], 10.0))
        bh = ah * np.exp(np.minimum(var[:, 3] * d[:, 3], 10.0))
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], 1)
        ih, iw = iv[b]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        order = np.argsort(-s)[:pre_nms_top_n]
        boxes, s2 = boxes[order], s[order]
        wv2 = boxes[:, 2] - boxes[:, 0] + off
        hv2 = boxes[:, 3] - boxes[:, 1] + off
        ok = (wv2 >= min_size) & (hv2 >= min_size)
        boxes, s2 = boxes[ok], s2[ok]
        if len(boxes):
            keep = np.asarray(_nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                                   scores=Tensor(jnp.asarray(s2))).numpy())
            keep = keep[:post_nms_top_n]
            boxes, s2 = boxes[keep], s2[keep]
        all_rois.append(boxes)
        all_scores.append(s2)
        all_nums.append(len(boxes))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois)
                              if all_rois else np.zeros((0, 4), np.float32)))
    rscores = Tensor(jnp.asarray(
        np.concatenate(all_scores) if all_scores
        else np.zeros((0,), np.float32)))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(
            np.asarray(all_nums, np.int32)))
    return rois, rscores


def read_file(filename: str, name=None) -> Tensor:
    """Raw file bytes as a uint8 tensor (reference: vision/ops.py
    read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode: str = "unchanged", name=None) -> Tensor:
    """Decode JPEG bytes to CHW uint8 (reference: vision/ops.py
    decode_jpeg → nvjpeg). Requires Pillow; raises a clear error in this
    zero-egress image when it is absent."""
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            "decode_jpeg needs Pillow, which is not in this zero-egress "
            "image; decode offline and feed .npy arrays instead") from e
    import io as _io

    raw = bytes(np.asarray(ensure_tensor(x).numpy(), np.uint8).tobytes())
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
