"""PP-OCR model family: DBNet text detection + CRNN/CTC recognition
(BASELINE.md config 5).

Reference parity: the reference repo ships the ops (deform_conv, CTC loss in
nn/functional) while the PP-OCR models live in PaddleOCR
(ppocr/modeling/architectures — det_db: backbones/det_mobilenet_v3.py +
necks/db_fpn.py + heads/det_db_head.py; rec_crnn: rnn neck + ctc head).
Made first-class here like the detection family (vision/models/detection.py).

TPU-native shape: static shapes throughout — DB outputs dense probability /
threshold maps (differentiable binarization stays elementwise, XLA fuses
it); CRNN runs its recurrence through nn.LSTM (lax.scan) and trains with the
pure-XLA ctc_loss (nn/functional/loss.py). Polygon extraction from the
probability map is a host-side numpy post-step, as it is in the reference
(db_postprocess.py runs on CPU there too).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ... import nn
from ...nn import functional as F
from ...ops._apply import apply_op, ensure_tensor
from ...ops.manipulation import concat as paddle_concat

__all__ = ["DBNet", "DBHead", "CRNN", "db_mobilenet_v3", "crnn_ctc",
           "db_loss"]


class _ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, act=True):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.relu(x) if self.act else x


class _DetBackbone(nn.Layer):
    """Compact MobileNetV3-style detector backbone → strides 4/8/16/32
    (ppocr backbones/det_mobilenet_v3.py, depthwise-separable blocks)."""

    def __init__(self, scale: float = 0.5):
        super().__init__()
        c = [int(16 * scale * m) for m in (1, 2, 4, 8, 12)]
        c = [max(8, v) for v in c]

        def dw_block(cin, cout, stride):
            return nn.Sequential(
                _ConvBNAct(cin, cin, 3, stride=stride),
                _ConvBNAct(cin, cout, 1))

        self.stem = _ConvBNAct(3, c[0], 3, stride=2)
        self.s4 = dw_block(c[0], c[1], 2)
        self.s8 = dw_block(c[1], c[2], 2)
        self.s16 = dw_block(c[2], c[3], 2)
        self.s32 = dw_block(c[3], c[4], 2)
        self.out_channels = c[1:]

    def forward(self, x):
        x = self.stem(x)
        f4 = self.s4(x)
        f8 = self.s8(f4)
        f16 = self.s16(f8)
        f32 = self.s32(f16)
        return [f4, f8, f16, f32]


class _DBFPN(nn.Layer):
    """DB-FPN: unify channels, top-down fusion, concat at stride 4
    (ppocr necks/db_fpn.py)."""

    def __init__(self, in_channels: Sequence[int], out_ch: int = 96):
        super().__init__()
        self.lateral = nn.LayerList(
            [nn.Conv2D(c, out_ch, 1, bias_attr=False) for c in in_channels])
        self.smooth = nn.LayerList(
            [nn.Conv2D(out_ch, out_ch // 4, 3, padding=1, bias_attr=False)
             for _ in in_channels])
        self.out_channels = out_ch

    def forward(self, feats):
        lat = [l(f) for l, f in zip(self.lateral, feats)]
        for i in range(len(lat) - 2, -1, -1):
            lat[i] = lat[i] + F.interpolate(lat[i + 1], scale_factor=2,
                                            mode="nearest")
        outs = []
        for i, (s, f) in enumerate(zip(self.smooth, lat)):
            o = s(f)
            if i > 0:
                o = F.interpolate(o, scale_factor=2 ** i, mode="nearest")
            outs.append(o)
        return paddle_concat(outs, axis=1)  # [B, out_ch, H/4, W/4]


class DBHead(nn.Layer):
    """Differentiable Binarization head: probability map P, threshold map T,
    approximate binary map B = sigmoid(k·(P − T))
    (ppocr heads/det_db_head.py; paper: Liao et al., DB, AAAI 2020)."""

    def __init__(self, in_ch: int, k: float = 50.0):
        super().__init__()
        self.k = k

        def branch():
            return nn.Sequential(
                _ConvBNAct(in_ch, in_ch // 4, 3),
                nn.Conv2DTranspose(in_ch // 4, in_ch // 4, 2, stride=2),
                nn.BatchNorm2D(in_ch // 4), nn.ReLU(),
                nn.Conv2DTranspose(in_ch // 4, 1, 2, stride=2))

        self.prob = branch()
        self.thresh = branch()

    def forward(self, x):
        import jax.numpy as jnp

        p = F.sigmoid(self.prob(x))
        t = F.sigmoid(self.thresh(x))
        k = self.k
        binary = apply_op(
            lambda pv, tv: 1.0 / (1.0 + jnp.exp(-k * (pv - tv))),
            [p, t], name="db_binarize")
        return p, t, binary


class DBNet(nn.Layer):
    """DB text detector: backbone + DB-FPN + DB head. forward(images) →
    (prob_map, thresh_map, binary_map), each [B, 1, H, W]."""

    def __init__(self, scale: float = 0.5, fpn_ch: int = 96):
        super().__init__()
        self.backbone = _DetBackbone(scale)
        self.neck = _DBFPN(self.backbone.out_channels, fpn_ch)
        self.head = DBHead(fpn_ch)

    def forward(self, images):
        return self.head(self.neck(self.backbone(images)))

    def postprocess(self, prob_map, thresh: float = 0.3,
                    min_area: int = 4) -> List[np.ndarray]:
        """Host-side box extraction: connected components of the binarized
        probability map → axis-aligned boxes [x0, y0, x1, y1] per image
        (the reference's db_postprocess.py is CPU-side too)."""
        pm = np.asarray(ensure_tensor(prob_map).numpy())[:, 0]
        out = []
        for img in pm > thresh:
            boxes = []
            seen = np.zeros_like(img, bool)
            H, W = img.shape
            for y in range(H):
                for x in range(W):
                    if img[y, x] and not seen[y, x]:
                        stack = [(y, x)]
                        seen[y, x] = True
                        ys, xs = [], []
                        while stack:
                            cy, cx = stack.pop()
                            ys.append(cy)
                            xs.append(cx)
                            for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
                                ny, nx = cy + dy, cx + dx
                                if (0 <= ny < H and 0 <= nx < W
                                        and img[ny, nx]
                                        and not seen[ny, nx]):
                                    seen[ny, nx] = True
                                    stack.append((ny, nx))
                        if len(ys) >= min_area:
                            boxes.append([min(xs), min(ys),
                                          max(xs) + 1, max(ys) + 1])
            out.append(np.asarray(boxes, np.float32).reshape(-1, 4))
        return out


def db_loss(prob, thresh, binary, gt_shrink, gt_thresh, gt_mask,
            alpha: float = 5.0, beta: float = 10.0):
    """DB training loss: BCE(prob) + dice(binary) + masked L1(thresh)
    (ppocr losses/det_db_loss.py, compact — no OHEM)."""
    import jax.numpy as jnp

    def fn(p, t, b, gs, gt, gm):
        p, t, b = p[:, 0], t[:, 0], b[:, 0]
        eps = 1e-6
        bce = -(gs * jnp.log(p + eps) + (1 - gs) * jnp.log(1 - p + eps))
        bce = bce.mean()
        inter = (b * gs).sum()
        dice = 1 - 2 * inter / (b.sum() + gs.sum() + eps)
        l1 = (jnp.abs(t - gt) * gm).sum() / (gm.sum() + eps)
        return alpha * bce + dice + beta * l1

    return apply_op(fn, [ensure_tensor(prob), ensure_tensor(thresh),
                         ensure_tensor(binary), ensure_tensor(gt_shrink),
                         ensure_tensor(gt_thresh), ensure_tensor(gt_mask)],
                    name="db_loss")


class CRNN(nn.Layer):
    """CRNN recognizer: conv feature extractor → squeeze height → BiLSTM →
    per-timestep vocabulary logits, trained with CTC
    (ppocr rec architectures: backbone + SequenceEncoder + CTCHead)."""

    def __init__(self, num_classes: int, in_channels: int = 3,
                 hidden: int = 96):
        super().__init__()
        self.convs = nn.Sequential(
            _ConvBNAct(in_channels, 32, 3), nn.MaxPool2D(2, 2),
            _ConvBNAct(32, 64, 3), nn.MaxPool2D(2, 2),
            _ConvBNAct(64, hidden, 3),
            nn.MaxPool2D(kernel_size=(2, 1), stride=(2, 1)),
        )
        self.rnn = nn.LSTM(hidden, hidden, direction="bidirect")
        self.fc = nn.Linear(2 * hidden, num_classes)
        self.num_classes = num_classes

    def forward(self, images):
        """images [B, C, H, W] → log-probs [T, B, num_classes] (CTC layout,
        T = W/4 timesteps)."""
        f = self.convs(images)              # [B, ch, H', W']
        f = f.mean(axis=2)                  # squeeze height → [B, ch, W']
        f = f.transpose([0, 2, 1])          # [B, T, ch]
        seq, _ = self.rnn(f)
        logits = self.fc(seq)               # [B, T, C]
        return F.log_softmax(logits, axis=-1).transpose([1, 0, 2])

    def loss(self, log_probs, labels, label_lengths):
        """CTC loss over the [T, B, C] log-probs (blank = 0)."""
        T, B = log_probs.shape[0], log_probs.shape[1]
        import numpy as _np

        from ...tensor import Tensor as _T
        import jax.numpy as jnp

        input_lengths = _T(jnp.full((B,), T, jnp.int32), stop_gradient=True)
        return F.ctc_loss(log_probs, labels, input_lengths,
                          ensure_tensor(label_lengths), blank=0)


def db_mobilenet_v3(scale: float = 0.5, **kw) -> DBNet:
    return DBNet(scale=scale, **kw)


def crnn_ctc(num_classes: int, **kw) -> CRNN:
    return CRNN(num_classes, **kw)
