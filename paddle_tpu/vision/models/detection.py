"""PP-YOLOE detection model family (BASELINE.md config 5).

Reference parity: the reference repo ships the detection *ops* in-tree
(vision/ops.py: yolo_box, matrix_nms, …) while the PP-YOLOE model lives in
PaddleDetection (ppdet/modeling/architectures/yolo.py,
backbones/cspresnet.py, necks/custom_pan.py, heads/ppyoloe_head.py). As with
the LLM zoo (models/gpt.py), the flagship benchmark model is made
first-class here.

TPU-native shape: anchor-free, fully static shapes — every level predicts a
dense [H·W] grid (no dynamic proposal lists, which XLA can't tile), and NMS
runs as the existing static-shape kernels in vision/ops.py. Training loss is
the PP-YOLOE recipe in compact form: varifocal-style BCE on classification,
GIoU on decoded boxes, and Distribution Focal Loss on the discretized
offsets, with a center-based positive assignment (a static simplification of
TAL that keeps the [N_gt, H·W] assignment dense).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ... import nn
from ...nn import functional as F
from ...ops.manipulation import concat as paddle_concat
from ...ops._apply import apply_op, ensure_tensor
from ...tensor import Tensor

__all__ = ["CSPResNet", "CSPPAN", "PPYOLOEHead", "PPYOLOE",
           "ppyoloe_s", "ppyoloe_m", "ppyoloe_l"]


class ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act="silu"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.silu(x) if self.act == "silu" else x


class CSPBlock(nn.Layer):
    """CSPResNet basic block: split, residual convs, concat, fuse
    (ppdet backbones/cspresnet.py BasicBlock + CSPResStage, compacted)."""

    def __init__(self, ch, n=1):
        super().__init__()
        mid = ch // 2
        self.left = ConvBNAct(ch, mid, 1)
        self.right = ConvBNAct(ch, mid, 1)
        self.blocks = nn.LayerList([
            nn.Sequential(ConvBNAct(mid, mid, 3), ConvBNAct(mid, mid, 3))
            for _ in range(n)])
        self.fuse = ConvBNAct(2 * mid, ch, 1)

    def forward(self, x):
        left = self.left(x)
        y = self.right(x)
        for b in self.blocks:
            y = y + b(y)
        return self.fuse(paddle_concat([left, y], axis=1))


class CSPResNet(nn.Layer):
    """Backbone emitting strides {8, 16, 32} feature maps."""

    def __init__(self, width=0.50, depth=0.33, in_channels=3):
        super().__init__()
        chs = [int(c * width) for c in (64, 128, 256, 512, 1024)]
        n = max(1, round(3 * depth))
        self.stem = nn.Sequential(
            ConvBNAct(in_channels, chs[0], 3, stride=2),
            ConvBNAct(chs[0], chs[0], 3))
        self.stages = nn.LayerList()
        for i in range(4):
            self.stages.append(nn.Sequential(
                ConvBNAct(chs[i], chs[i + 1], 3, stride=2),
                CSPBlock(chs[i + 1], n)))
        self.out_channels = chs[2:]

    def forward(self, x):
        x = self.stem(x)
        outs = []
        for i, stage in enumerate(self.stages):
            x = stage(x)
            if i >= 1:
                outs.append(x)
        return outs  # [C3/8, C4/16, C5/32]


class CSPPAN(nn.Layer):
    """PAN neck: top-down then bottom-up fusion
    (ppdet necks/custom_pan.py CustomCSPPAN, compacted)."""

    def __init__(self, in_channels: Sequence[int]):
        super().__init__()
        c3, c4, c5 = in_channels
        self.reduce5 = ConvBNAct(c5, c4, 1)
        self.td4 = CSPBlock(2 * c4)
        self.merge4 = ConvBNAct(2 * c4, c4, 1)
        self.reduce4 = ConvBNAct(c4, c3, 1)
        self.td3 = CSPBlock(2 * c3)
        self.merge3 = ConvBNAct(2 * c3, c3, 1)
        self.down3 = ConvBNAct(c3, c3, 3, stride=2)
        self.bu4 = ConvBNAct(c3 + c4, c4, 1)
        self.down4 = ConvBNAct(c4, c4, 3, stride=2)
        self.bu5 = ConvBNAct(c4 + c4, c4, 1)
        self.out_channels = [c3, c4, c4]

    def forward(self, feats):
        c3, c4, c5 = feats
        p5 = self.reduce5(c5)
        up5 = F.interpolate(p5, scale_factor=2, mode="nearest")
        p4 = self.merge4(self.td4(paddle_concat([up5, c4], axis=1)))
        p4r = self.reduce4(p4)
        up4 = F.interpolate(p4r, scale_factor=2, mode="nearest")
        p3 = self.merge3(self.td3(paddle_concat([up4, c3], axis=1)))
        n4 = self.bu4(paddle_concat([self.down3(p3), p4], axis=1))
        n5 = self.bu5(paddle_concat([self.down4(n4), p5], axis=1))
        return [p3, n4, n5]


class PPYOLOEHead(nn.Layer):
    """Anchor-free decoupled head with DFL regression
    (ppdet heads/ppyoloe_head.py, compact: ESE attention dropped)."""

    def __init__(self, in_channels: Sequence[int], num_classes: int = 80,
                 reg_max: int = 16, strides=(8, 16, 32)):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.strides = list(strides)
        self.stem_cls = nn.LayerList(
            [ConvBNAct(c, c, 1) for c in in_channels])
        self.stem_reg = nn.LayerList(
            [ConvBNAct(c, c, 1) for c in in_channels])
        self.pred_cls = nn.LayerList(
            [nn.Conv2D(c, num_classes, 3, padding=1) for c in in_channels])
        self.pred_reg = nn.LayerList(
            [nn.Conv2D(c, 4 * (reg_max + 1), 3, padding=1)
             for c in in_channels])
        # DFL projection: discretized offset bins -> expectation
        self.proj = Tensor(jnp.arange(reg_max + 1, dtype=jnp.float32),
                           stop_gradient=True)

    def forward(self, feats):
        """Returns per-level (cls_logits [B,HW,C], reg_logits
        [B,HW,4,reg_max+1], anchor centers [HW,2], stride)."""
        outs = []
        for i, f in enumerate(feats):
            B = f.shape[0]
            H, W = f.shape[2], f.shape[3]
            cls = self.pred_cls[i](self.stem_cls[i](f) + f)
            reg = self.pred_reg[i](self.stem_reg[i](f))
            cls = cls.transpose([0, 2, 3, 1]).reshape([B, H * W,
                                                       self.num_classes])
            reg = reg.transpose([0, 2, 3, 1]).reshape(
                [B, H * W, 4, self.reg_max + 1])
            s = self.strides[i]
            yy, xx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
            centers = Tensor(jnp.asarray(
                np.stack([(xx.reshape(-1) + 0.5) * s,
                          (yy.reshape(-1) + 0.5) * s], axis=-1),
                jnp.float32), stop_gradient=True)
            outs.append((cls, reg, centers, s))
        return outs

    def decode(self, reg, centers, stride):
        """DFL expectation -> ltrb distances -> xyxy boxes."""
        probs = F.softmax(reg, axis=-1)
        dist = apply_op(
            lambda p, pr: jnp.einsum("bnkr,r->bnk", p, pr),
            [probs, self.proj], name="dfl_project")  # [B, HW, 4]

        def mk(dv, cv):
            lt, rb = dv[..., :2], dv[..., 2:]
            return jnp.concatenate([cv[None] - lt * stride,
                                    cv[None] + rb * stride], axis=-1)

        return apply_op(mk, [dist, centers], name="dfl_decode")


def _giou(a, b):
    """GIoU between [N,4] xyxy box arrays (jnp)."""
    lt = jnp.maximum(a[..., :2], b[..., :2])
    rb = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0) * \
        jnp.clip(a[..., 3] - a[..., 1], 0)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0) * \
        jnp.clip(b[..., 3] - b[..., 1], 0)
    union = area_a + area_b - inter
    iou = inter / jnp.maximum(union, 1e-9)
    clt = jnp.minimum(a[..., :2], b[..., :2])
    crb = jnp.maximum(a[..., 2:], b[..., 2:])
    cwh = jnp.clip(crb - clt, 0)
    chull = jnp.maximum(cwh[..., 0] * cwh[..., 1], 1e-9)
    return iou - (chull - union) / chull


class PPYOLOE(nn.Layer):
    """PP-YOLOE: CSPResNet + CSPPAN + ET-head.

    forward(images) -> per-level raw predictions;
    loss(preds, gt_boxes, gt_labels, gt_mask) -> scalar training loss;
    predict(images, ...) -> (boxes [N,4], scores [N], labels [N]) via the
    static-shape NMS kernels in vision/ops.py.
    """

    def __init__(self, num_classes: int = 80, width: float = 0.50,
                 depth: float = 0.33, reg_max: int = 16):
        super().__init__()
        self.backbone = CSPResNet(width=width, depth=depth)
        self.neck = CSPPAN(self.backbone.out_channels)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes,
                                reg_max=reg_max)
        self.num_classes = num_classes

    def forward(self, images):
        return self.head(self.neck(self.backbone(images)))

    # -------------------------------------------------------------- loss
    def loss(self, preds, gt_boxes, gt_labels, gt_mask):
        """gt_boxes [B, M, 4] xyxy; gt_labels [B, M] int; gt_mask [B, M]
        (1 = real box, 0 = padding). Center-inside positive assignment."""
        gt_boxes = ensure_tensor(gt_boxes)
        gt_labels = ensure_tensor(gt_labels)
        gt_mask = ensure_tensor(gt_mask)
        total = None
        for cls, reg, centers, stride in preds:
            boxes = self.head.decode(reg, centers, stride)
            lvl = apply_op(
                lambda c, r, bx, gb, gl, gm, _centers=centers._value,
                       _stride=stride: _ppyoloe_level_loss(
                    c, r, bx, gb, gl, gm, _centers, _stride,
                    self.num_classes, self.head.reg_max),
                [cls, reg, boxes, gt_boxes, gt_labels, gt_mask],
                name="ppyoloe_loss")
            total = lvl if total is None else total + lvl
        return total

    # ----------------------------------------------------------- predict
    def predict(self, images, score_thresh: float = 0.3,
                iou_thresh: float = 0.5, top_k: Optional[int] = 100):
        from ..ops import nms

        preds = self.forward(images)
        all_boxes, all_scores, all_labels = [], [], []
        for cls, reg, centers, stride in preds:
            boxes = self.head.decode(reg, centers, stride)
            scores = F.sigmoid(cls)
            all_boxes.append(boxes)
            all_scores.append(scores)
        boxes = paddle_concat(all_boxes, axis=1)[0]          # [N, 4]
        scores = paddle_concat(all_scores, axis=1)[0]        # [N, C]
        best = scores.max(axis=-1)
        label = scores.argmax(axis=-1)
        keepable = np.asarray((best > score_thresh).numpy())
        idx = np.nonzero(keepable)[0]
        if idx.size == 0:
            return (np.zeros((0, 4), np.float32), np.zeros(0, np.float32),
                    np.zeros(0, np.int64))
        b = Tensor(boxes._value[idx])
        s = Tensor(best._value[idx])
        kept = nms(b, iou_threshold=iou_thresh, scores=s, top_k=top_k)
        ki = np.asarray(kept.numpy())
        return (np.asarray(b.numpy())[ki], np.asarray(s.numpy())[ki],
                np.asarray(label.numpy())[idx][ki])


def _ppyoloe_level_loss(cls_logits, reg_logits, boxes, gt_boxes, gt_labels,
                        gt_mask, centers, stride, num_classes, reg_max):
    """One level's loss, pure jnp (runs under apply_op/vjp)."""
    B, N, C = cls_logits.shape
    M = gt_boxes.shape[1]
    cx = centers[None, None, :, 0]                       # [1,1,N]
    cy = centers[None, None, :, 1]
    inside = ((cx >= gt_boxes[..., 0:1]) & (cx <= gt_boxes[..., 2:3])
              & (cy >= gt_boxes[..., 1:2]) & (cy <= gt_boxes[..., 3:4]))
    inside = inside & (gt_mask[..., None] > 0)           # [B,M,N]
    # each anchor takes the smallest-area gt containing it
    area = ((gt_boxes[..., 2] - gt_boxes[..., 0])
            * (gt_boxes[..., 3] - gt_boxes[..., 1]))     # [B,M]
    big = jnp.float32(1e12)
    cand = jnp.where(inside, area[..., None], big)       # [B,M,N]
    gt_idx = jnp.argmin(cand, axis=1)                    # [B,N]
    pos = jnp.min(cand, axis=1) < big                    # [B,N]

    tgt_box = jnp.take_along_axis(
        gt_boxes, gt_idx[..., None].repeat(4, -1), axis=1)   # [B,N,4]
    tgt_lab = jnp.take_along_axis(gt_labels, gt_idx, axis=1)  # [B,N]

    # classification: BCE with IoU-weighted positives (varifocal-lite)
    iou = jax.lax.stop_gradient(_giou(boxes, tgt_box) * 0.5 + 0.5)
    onehot = jax.nn.one_hot(tgt_lab, C) * jnp.where(pos, iou, 0.0)[..., None]
    p = jax.nn.sigmoid(cls_logits)
    bce = -(onehot * jnp.log(jnp.clip(p, 1e-9))
            + (1 - onehot) * jnp.log(jnp.clip(1 - p, 1e-9)))
    cls_loss = bce.sum() / jnp.maximum(pos.sum(), 1)

    # regression on positives: GIoU + DFL
    giou_loss = jnp.where(pos, 1.0 - _giou(boxes, tgt_box), 0.0).sum() \
        / jnp.maximum(pos.sum(), 1)
    # DFL: distance targets in bins
    lt = jnp.stack([(cx[0, 0] - tgt_box[..., 0]) / stride,
                    (cy[0, 0] - tgt_box[..., 1]) / stride,
                    (tgt_box[..., 2] - cx[0, 0]) / stride,
                    (tgt_box[..., 3] - cy[0, 0]) / stride], axis=-1)
    tgt = jnp.clip(lt, 0, reg_max - 0.01)                # [B,N,4]
    tl = jnp.floor(tgt)
    wr = tgt - tl
    logp = jax.nn.log_softmax(reg_logits, axis=-1)
    li = tl.astype(jnp.int32)
    dfl = -(jnp.take_along_axis(logp, li[..., None], -1)[..., 0] * (1 - wr)
            + jnp.take_along_axis(logp, (li + 1)[..., None], -1)[..., 0] * wr)
    dfl_loss = jnp.where(pos[..., None], dfl, 0.0).sum() \
        / jnp.maximum(pos.sum() * 4, 1)
    return cls_loss + 2.0 * giou_loss + 0.5 * dfl_loss


def ppyoloe_s(num_classes: int = 80, **kw) -> PPYOLOE:
    return PPYOLOE(num_classes, width=0.50, depth=0.33, **kw)


def ppyoloe_m(num_classes: int = 80, **kw) -> PPYOLOE:
    return PPYOLOE(num_classes, width=0.75, depth=0.67, **kw)


def ppyoloe_l(num_classes: int = 80, **kw) -> PPYOLOE:
    return PPYOLOE(num_classes, width=1.0, depth=1.0, **kw)
