"""DenseNet / ShuffleNetV2 / GoogLeNet / InceptionV3 (reference:
python/paddle/vision/models/{densenet,shufflenetv2,googlenet,inceptionv3}.py).
"""
from __future__ import annotations

import paddle_tpu as paddle

from ... import nn
from ...nn import functional as F

__all__ = [
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264", "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "GoogLeNet", "googlenet", "InceptionV3",
    "inception_v3",
]


# ---------------------------------------------------------------- DenseNet
class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.drop_rate = drop_rate

    def forward(self, x):
        out = self.conv1(F.relu(self.norm1(x)))
        out = self.conv2(F.relu(self.norm2(out)))
        if self.drop_rate:
            out = F.dropout(out, self.drop_rate, training=self.training)
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(F.relu(self.norm(x))))


_DENSE_CFG = {
    121: (32, [6, 12, 24, 16], 64), 161: (48, [6, 12, 36, 24], 96),
    169: (32, [6, 12, 32, 32], 64), 201: (32, [6, 12, 48, 32], 64),
    264: (32, [6, 12, 64, 48], 64),
}


class DenseNet(nn.Layer):
    """reference: vision/models/densenet.py."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        growth, block_cfg, num_init = _DENSE_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        ]
        ch = num_init
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)


# ------------------------------------------------------------ ShuffleNetV2
def _channel_shuffle(x, groups):
    return F.channel_shuffle(x, groups)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act_layer=None):
        super().__init__()
        self.stride = stride
        act_layer = act_layer or nn.ReLU
        branch = out_ch // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer(),
                nn.Conv2D(branch, branch, 3, stride=1, padding=1, groups=branch,
                          bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer(),
            )
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer(),
            )
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer(),
                nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                          groups=branch, bias_attr=False),
                nn.BatchNorm2D(branch),
                nn.Conv2D(branch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer(),
            )

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    """reference: vision/models/shufflenetv2.py."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        cfg = _SHUFFLE_CFG[scale]
        act_layer = {"relu": nn.ReLU, "swish": nn.Swish}.get(act)
        if act_layer is None:
            raise ValueError(f"unsupported act {act!r}; use 'relu'/'swish'")
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, cfg[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(cfg[0]), act_layer(),
        )
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_ch = cfg[0]
        for i, (out_ch, repeat) in enumerate(zip(cfg[1:4], [4, 8, 4])):
            units = [_ShuffleUnit(in_ch, out_ch, 2, act_layer)]
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_ch, out_ch, 1, act_layer))
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, cfg[4], 1, bias_attr=False),
            nn.BatchNorm2D(cfg[4]), act_layer(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(cfg[4], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    """reference: shufflenet_v2_swish — the x1.0 net with swish
    activations throughout."""
    return _shufflenet(1.0, pretrained, act="swish", **kwargs)


# -------------------------------------------------------------- GoogLeNet
class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c2_red, c2, c3_red, c3, c4):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(
            nn.Conv2D(in_ch, c2_red, 1), nn.ReLU(),
            nn.Conv2D(c2_red, c2, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(
            nn.Conv2D(in_ch, c3_red, 1), nn.ReLU(),
            nn.Conv2D(c3_red, c3, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(
            nn.MaxPool2D(3, 1, padding=1), nn.Conv2D(in_ch, c4, 1), nn.ReLU())

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    """reference: vision/models/googlenet.py (aux heads active in training)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        )
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux classifiers (reference returns (out, aux1, aux2))
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D((4, 4)), nn.Conv2D(512, 128, 1), nn.ReLU(),
                nn.Flatten(), nn.Linear(2048, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D((4, 4)), nn.Conv2D(528, 128, 1), nn.ReLU(),
                nn.Flatten(), nn.Linear(2048, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        aux1 = self.aux1(x) if (self.training and self.num_classes > 0) else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if (self.training and self.num_classes > 0) else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        if self.training and self.num_classes > 0:
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return GoogLeNet(**kwargs)


# ------------------------------------------------------------- InceptionV3
class _BasicConv(nn.Layer):
    def __init__(self, in_ch, out_ch, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(out_ch)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_ch):
        super().__init__()
        self.b1 = _BasicConv(in_ch, 64, 1)
        self.b5 = nn.Sequential(_BasicConv(in_ch, 48, 1),
                                _BasicConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BasicConv(in_ch, 64, 1),
                                _BasicConv(64, 96, 3, padding=1),
                                _BasicConv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(in_ch, pool_ch, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _BasicConv(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_BasicConv(in_ch, 64, 1),
                                 _BasicConv(64, 96, 3, padding=1),
                                 _BasicConv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_ch, c7):
        super().__init__()
        self.b1 = _BasicConv(in_ch, 192, 1)
        self.b7 = nn.Sequential(
            _BasicConv(in_ch, c7, 1),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _BasicConv(in_ch, c7, 1),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(in_ch, 192, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_BasicConv(in_ch, 192, 1),
                                _BasicConv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _BasicConv(in_ch, 192, 1),
            _BasicConv(192, 192, (1, 7), padding=(0, 3)),
            _BasicConv(192, 192, (7, 1), padding=(3, 0)),
            _BasicConv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _BasicConv(in_ch, 320, 1)
        self.b3_stem = _BasicConv(in_ch, 384, 1)
        self.b3_a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_BasicConv(in_ch, 448, 1),
                                      _BasicConv(448, 384, 3, padding=1))
        self.b3d_a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BasicConv(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        b3 = paddle.concat([self.b3_a(s), self.b3_b(s)], axis=1)
        d = self.b3d_stem(x)
        b3d = paddle.concat([self.b3d_a(d), self.b3d_b(d)], axis=1)
        return paddle.concat([self.b1(x), b3, b3d, self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    """reference: vision/models/inceptionv3.py (299x299 inputs)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, stride=2), _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _BasicConv(64, 80, 1), _BasicConv(80, 192, 3), nn.MaxPool2D(3, 2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160), _InceptionC(768, 160),
            _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return InceptionV3(**kwargs)
