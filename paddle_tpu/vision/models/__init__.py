"""paddle_tpu.vision.models — the vision model zoo (reference:
python/paddle/vision/models/__init__.py inventory, SURVEY.md §2.4)."""
from .detection import (  # noqa: F401
    CSPPAN, CSPResNet, PPYOLOE, PPYOLOEHead, ppyoloe_l, ppyoloe_m,
    ppyoloe_s,
)
from .ocr import (  # noqa: F401
    CRNN, DBHead, DBNet, crnn_ctc, db_loss, db_mobilenet_v3,
)
from .extra_nets import (  # noqa: F401
    DenseNet, GoogLeNet, InceptionV3, ShuffleNetV2, densenet121, densenet161,
    densenet169, densenet201, densenet264, googlenet, inception_v3,
    shufflenet_v2_x0_25, shufflenet_v2_x0_33, shufflenet_v2_x0_5,
    shufflenet_v2_swish, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0,
)
from .mobilenet import (  # noqa: F401
    MobileNetV1, MobileNetV2, MobileNetV3Large, MobileNetV3Small,
    mobilenet_v1, mobilenet_v2, mobilenet_v3_large, mobilenet_v3_small,
)
from .resnet import (  # noqa: F401
    BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34, resnet50,
    resnet101, resnet152, resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d, wide_resnet50_2,
    wide_resnet101_2,
)
from .simple_nets import (  # noqa: F401
    AlexNet, LeNet, SqueezeNet, VGG, alexnet, squeezenet1_0, squeezenet1_1,
    vgg11, vgg13, vgg16, vgg19,
)

__all__ = [
    "PPYOLOE", "ppyoloe_s", "ppyoloe_m", "ppyoloe_l", "CSPResNet",
    "CSPPAN", "PPYOLOEHead", "DBNet", "DBHead", "CRNN", "db_mobilenet_v3",
    "crnn_ctc", "db_loss",
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d", "resnext50_64x4d",
    "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
    "resnext152_64x4d", "BasicBlock", "BottleneckBlock",
    "LeNet", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
    "MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
    "mobilenet_v3_large",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264", "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_swish",
    "shufflenet_v2_x2_0", "GoogLeNet", "googlenet", "InceptionV3",
    "inception_v3",
]
