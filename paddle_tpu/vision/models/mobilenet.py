"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/
mobilenetv1.py, mobilenetv2.py, mobilenetv3.py)."""
from __future__ import annotations

from ... import nn
from ...nn import functional as F

__all__ = [
    "MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
    "mobilenet_v3_large",
]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_ch, out_ch, k=3, stride=1, groups=1, act=nn.ReLU):
        pad = (k - 1) // 2
        layers = [
            nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_ch),
        ]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class MobileNetV1(nn.Layer):
    """reference: vision/models/mobilenetv1.py — depthwise-separable stack."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # (out, stride) per dw/pw pair
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        in_ch = int(32 * scale)
        layers = [_ConvBNReLU(3, in_ch, 3, stride=2)]
        for out, s in cfg:
            out_ch = int(out * scale)
            layers.append(_ConvBNReLU(in_ch, in_ch, 3, stride=s, groups=in_ch))
            layers.append(_ConvBNReLU(in_ch, out_ch, 1))
            in_ch = out_ch
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(in_ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return MobileNetV1(scale=scale, **kwargs)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden,
                        act=nn.ReLU6),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    """reference: vision/models/mobilenetv2.py."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_ch = _make_divisible(32 * scale)
        last_ch = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, in_ch, 3, stride=2, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(in_ch, out_ch,
                                                s if i == 0 else 1, t))
                in_ch = out_ch
        layers.append(_ConvBNReLU(in_ch, last_ch, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return MobileNetV2(scale=scale, **kwargs)


class _SEBlock(nn.Layer):
    def __init__(self, ch, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(ch, ch // r, 1)
        self.fc2 = nn.Conv2D(ch // r, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, inp, hidden, out, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        layers = []
        if hidden != inp:
            layers.append(_ConvBNReLU(inp, hidden, 1, act=act))
        layers.append(_ConvBNReLU(hidden, hidden, k, stride=stride,
                                  groups=hidden, act=act))
        if use_se:
            layers.append(_SEBlock(hidden))
        layers += [nn.Conv2D(hidden, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


_V3_LARGE = [
    # k, exp, out, se, act, s
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]

_V3_SMALL = [
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(16 * scale)
        layers = [_ConvBNReLU(3, in_ch, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out, se, act, s in cfg:
            hidden = _make_divisible(exp * scale)
            out_ch = _make_divisible(out * scale)
            layers.append(_MBV3Block(in_ch, hidden, out_ch, k, s, se, act))
            in_ch = out_ch
        last = _make_divisible(last_exp * scale)
        layers.append(_ConvBNReLU(in_ch, last, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            out_dim = 1280 if last_exp == 960 else 1024
            self.classifier = nn.Sequential(
                nn.Linear(last, out_dim), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(out_dim, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled (zero egress)")
    return MobileNetV3Small(scale=scale, **kwargs)
