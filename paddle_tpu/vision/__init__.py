"""paddle_tpu.vision (reference: python/paddle/vision/ — models, transforms,
datasets, ops; SURVEY.md §2.4)."""
from . import datasets, models, ops, transforms  # noqa: F401
from .image import get_image_backend, image_load, set_image_backend  # noqa: F401
from .models import *  # noqa: F401,F403

__all__ = ["models", "transforms", "datasets", "ops",
           "set_image_backend", "get_image_backend", "image_load"]
