"""Vision ops (reference: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, box_coder, yolo_box, yolo_loss, distribute_fpn_proposals...).

TPU notes: detection post-processing is dynamic-shape by nature; the kernels
here keep static shapes (fixed-size outputs with validity masks / -1 padding)
so they compile once — the paddle API shape contract is preserved where
possible and documented where padded.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import functional as F  # noqa: F401 (parity surface)
from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor

from .ops_extra import (  # noqa: F401
    PSRoIPool, decode_jpeg, distribute_fpn_proposals, generate_proposals,
    matrix_nms, prior_box, psroi_pool, read_file, yolo_loss,
)

__all__ = ["yolo_loss", "prior_box", "matrix_nms", "psroi_pool", "PSRoIPool",
           "distribute_fpn_proposals", "generate_proposals", "read_file",
           "decode_jpeg",
           "nms", "roi_align", "roi_pool", "box_coder", "yolo_box", "box_area",
           "box_iou", "deform_conv2d", "DeformConv2D", "RoIAlign", "RoIPool"]


def box_area(boxes):
    """reference: vision/ops.py box_area ([N,4] xyxy)."""
    b = ensure_tensor(boxes)
    return apply_op(
        lambda v: (v[:, 2] - v[:, 0]) * (v[:, 3] - v[:, 1]), [b], name="box_area")


def _pairwise_iou(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / (area_a[:, None] + area_b[None, :] - inter + 1e-10)


def box_iou(boxes1, boxes2):
    a, b = ensure_tensor(boxes1), ensure_tensor(boxes2)
    return apply_op(_pairwise_iou, [a, b], name="box_iou")


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None):
    """reference: vision/ops.py nms — greedy suppression, returns kept indices
    sorted by score. Static-shape kernel: O(N^2) IoU matrix + iterative mask
    via lax.fori_loop (compiles once per N)."""
    b = ensure_tensor(boxes)
    n = b.shape[0]
    if scores is None:
        scores_t = Tensor(jnp.arange(n, 0, -1, dtype=jnp.float32))
    else:
        scores_t = ensure_tensor(scores)

    def fn(bv, sv, *cat):
        order = jnp.argsort(-sv)
        bb = bv[order]
        iou = _pairwise_iou(bb, bb)
        if cat:  # category-aware: only same-category boxes suppress
            cv = cat[0][order]
            iou = jnp.where(cv[:, None] == cv[None, :], iou, 0.0)

        def body(i, keep):
            # suppress i iff a kept higher-scored j overlaps it
            suppressed = jnp.any(
                jnp.where(jnp.arange(n) < i,
                          (iou[:, i] > iou_threshold) & keep.astype(bool),
                          False))
            return keep.at[i].set(jnp.where(suppressed, False, True))

        keep = jnp.ones((n,), dtype=bool)
        keep = jax.lax.fori_loop(1, n, body, keep)
        kept_sorted = jnp.where(keep, order, -1)
        # compact: stable partition of valid entries first
        idx = jnp.argsort(~keep)  # True(keep) first, stable
        return kept_sorted[idx]

    ins = [b, scores_t]
    if category_idxs is not None:
        ins.append(ensure_tensor(category_idxs))
    out = apply_op(fn, ins, differentiable=False, name="nms")
    # host-side compaction to paddle's dynamic shape (eager only)
    if not isinstance(out._value, jax.core.Tracer):
        vals = np.asarray(out._value)
        vals = vals[vals >= 0]
        if top_k is not None:
            vals = vals[:top_k]
        return Tensor(jnp.asarray(vals, dtype=jnp.int64))
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """reference: vision/ops.py roi_align (phi roi_align kernel) — bilinear
    sampling of box regions to [num_rois, C, out_h, out_w]."""
    xt, bt = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = ensure_tensor(boxes_num)

    def fn(feat, rois, rois_num):
        N, C, H, W = feat.shape
        # map each roi to its batch image by boxes_num
        counts = rois_num.astype(jnp.int32)
        batch_of = jnp.repeat(jnp.arange(N), counts, total_repeat_length=rois.shape[0])
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid [R, oh*sr, ow*sr]
        ys = (y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :] * rh[:, None]
              / (oh * sr))
        xs = (x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :] * rw[:, None]
              / (ow * sr))

        def bilinear(img, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            # explicit gather: [C, ny, nx]
            v00 = img[:, y0][:, :, x0]
            v01 = img[:, y0][:, :, x1_]
            v10 = img[:, y1_][:, :, x0]
            v11 = img[:, y1_][:, :, x1_]
            wy_ = wy[None, :, None]
            wx_ = wx[None, None, :]
            return (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                    + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)

        def per_roi(i):
            img = feat[batch_of[i]]
            samp = bilinear(img, ys[i], xs[i])  # [C, oh*sr, ow*sr]
            return samp.reshape(C, oh, sr, ow, sr).mean(axis=(2, 4))

        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return apply_op(fn, [xt, bt, Tensor(bn._value, stop_gradient=True)],
                    name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None):
    """reference: vision/ops.py roi_pool — max-pool variant via dense sampling."""
    xt, bt = ensure_tensor(x), ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = ensure_tensor(boxes_num)

    def fn(feat, rois, rois_num):
        N, C, H, W = feat.shape
        counts = rois_num.astype(jnp.int32)
        batch_of = jnp.repeat(jnp.arange(N), counts,
                              total_repeat_length=rois.shape[0])
        sr = 4
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        ys = y1[:, None] + (jnp.arange(oh * sr) + 0.5)[None, :] * rh[:, None] / (oh * sr)
        xs = x1[:, None] + (jnp.arange(ow * sr) + 0.5)[None, :] * rw[:, None] / (ow * sr)

        def per_roi(i):
            img = feat[batch_of[i]]
            yi = jnp.clip(jnp.round(ys[i]).astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(jnp.round(xs[i]).astype(jnp.int32), 0, W - 1)
            samp = img[:, yi][:, :, xi]
            return samp.reshape(C, oh, sr, ow, sr).max(axis=(2, 4))

        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return apply_op(fn, [xt, bt, Tensor(bn._value, stop_gradient=True)],
                    name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None):
    """reference: vision/ops.py box_coder (phi box_coder kernel)."""
    pb, tb = ensure_tensor(prior_box), ensure_tensor(target_box)
    pbv = ensure_tensor(prior_box_var) if prior_box_var is not None else None

    def fn(p, t, *v):
        norm = 0.0 if box_normalized else 1.0
        pw = p[:, 2] - p[:, 0] + norm
        ph = p[:, 3] - p[:, 1] + norm
        pcx = p[:, 0] + pw * 0.5
        pcy = p[:, 1] + ph * 0.5
        var = v[0] if v else jnp.ones((1, 4), p.dtype)
        if code_type == "encode_center_size":
            tw = t[:, 2] - t[:, 0] + norm
            th = t[:, 3] - t[:, 1] + norm
            tcx = t[:, 0] + tw * 0.5
            tcy = t[:, 1] + th * 0.5
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :]),
            ], axis=-1)
            return out / var.reshape(1, -1, 4)
        # decode_center_size
        d = t * var.reshape(1, -1, 4) if v else t
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :], pcx[None, :],
                                    pcy[None, :])
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None], pcx[:, None],
                                    pcy[:, None])
        ocx = d[..., 0] * pw_ + pcx_
        ocy = d[..., 1] * ph_ + pcy_
        ow_ = jnp.exp(d[..., 2]) * pw_
        oh_ = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([ocx - ow_ / 2, ocy - oh_ / 2,
                          ocx + ow_ / 2 - norm, ocy + oh_ / 2 - norm], axis=-1)

    ins = [pb, tb] + ([pbv] if pbv is not None else [])
    return apply_op(fn, ins, name="box_coder")


def yolo_box(x, img_size, anchors, class_num: int, conf_thresh: float = 0.01,
             downsample_ratio: int = 32, clip_bbox: bool = True, name=None,
             scale_x_y: float = 1.0, iou_aware: bool = False,
             iou_aware_factor: float = 0.5):
    """reference: vision/ops.py yolo_box (phi yolo_box kernel) — decode YOLO
    head predictions to boxes+scores. Returns (boxes [N, anchors*H*W, 4],
    scores [N, anchors*H*W, class_num]); sub-threshold boxes zeroed."""
    xt, st = ensure_tensor(x), ensure_tensor(img_size)
    na = len(anchors) // 2
    anc = jnp.asarray(np.asarray(anchors, dtype="float32").reshape(na, 2))

    def fn(v, imgs):
        N, C, H, W = v.shape
        # iou-aware head layout (phi yolo_box_util.h GetEntryIndex/GetIoUIndex):
        # first na channels are per-anchor IoU logits, then the usual
        # na×(5+class_num) blocks; conf = obj^(1-f) * iou^f
        if iou_aware:
            iou = jax.nn.sigmoid(v[:, :na].reshape(N, na, H, W))
            v = v[:, na:]
        v = v.reshape(N, na, -1, H, W)
        box_attr = v.shape[2]
        gx = (jnp.arange(W) + 0.5)[None, None, None, :]
        gy = (jnp.arange(H) + 0.5)[None, None, :, None]
        sx = jax.nn.sigmoid(v[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = jax.nn.sigmoid(v[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        cx = (jnp.floor(gx) + sx) / W
        cy = (jnp.floor(gy) + sy) / H
        input_h = downsample_ratio * H
        input_w = downsample_ratio * W
        bw = jnp.exp(v[:, :, 2]) * anc[None, :, 0, None, None] / input_w
        bh = jnp.exp(v[:, :, 3]) * anc[None, :, 1, None, None] / input_h
        conf = jax.nn.sigmoid(v[:, :, 4])
        if iou_aware:
            f = jnp.asarray(iou_aware_factor, v.dtype)
            conf = conf ** (1.0 - f) * iou ** f
        cls = jax.nn.sigmoid(v[:, :, 5:5 + class_num]) * conf[:, :, None]
        imh = imgs[:, 0].astype(v.dtype)[:, None, None, None]
        imw = imgs[:, 1].astype(v.dtype)[:, None, None, None]
        x1 = (cx - bw / 2) * imw
        y1 = (cy - bh / 2) * imh
        x2 = (cx + bw / 2) * imw
        y2 = (cy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0)
            y1 = jnp.clip(y1, 0)
            x2 = jnp.minimum(x2, imw - 1)
            y2 = jnp.minimum(y2, imh - 1)
        mask = (conf > conf_thresh).astype(v.dtype)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * mask[..., None]
        boxes = boxes.transpose(0, 1, 2, 3, 4).reshape(N, -1, 4)
        scores = (cls * mask[:, :, None]).transpose(0, 1, 3, 4, 2).reshape(
            N, -1, class_num)
        return boxes, scores

    return apply_op(fn, [xt, Tensor(st._value, stop_gradient=True)],
                    name="yolo_box")


def deform_conv2d(x, offset, weight, mask=None, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, name=None):
    """reference: vision/ops.py deform_conv2d — v1/v2 deformable convolution
    via explicit bilinear sampling + matmul (MXU-friendly im2col form)."""
    xt = ensure_tensor(x)
    ot = ensure_tensor(offset)
    wt = ensure_tensor(weight)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def fn(xv, off, w, *rest):
        N, C, H, W = xv.shape
        Co, Cg, kh, kw = w.shape
        ph, pw = padding
        xp = jnp.pad(xv, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        Hp, Wp = H + 2 * ph, W + 2 * pw
        oh = (Hp - (dilation[0] * (kh - 1) + 1)) // stride[0] + 1
        ow = (Wp - (dilation[1] * (kw - 1) + 1)) // stride[1] + 1
        # offsets [N, dg, 2(y,x), k, oh, ow]; optional modulation mask after
        off = off.reshape(N, deformable_groups, 2, kh * kw, oh, ow)
        mask_v = None
        if mask is not None:
            mask_v = rest[0].reshape(N, deformable_groups, kh * kw, oh, ow)
        # sampling coords per (n, dg, k, i, j)
        kyx = jnp.stack(jnp.meshgrid(jnp.arange(kh) * dilation[0],
                                     jnp.arange(kw) * dilation[1],
                                     indexing="ij"), 0).reshape(2, -1)
        gy = jnp.arange(oh) * stride[0]
        gx = jnp.arange(ow) * stride[1]
        sy = (gy[None, None, None, :, None] + kyx[0][None, None, :, None, None]
              + off[:, :, 0])
        sx = (gx[None, None, None, None, :] + kyx[1][None, None, :, None, None]
              + off[:, :, 1])

        def bilin(img2d, yy2, xx2):
            y0 = jnp.floor(yy2)
            x0 = jnp.floor(xx2)
            wy = yy2 - y0
            wx = xx2 - x0
            y0i = jnp.clip(y0.astype(jnp.int32), 0, Hp - 1)
            x0i = jnp.clip(x0.astype(jnp.int32), 0, Wp - 1)
            y1i = jnp.clip(y0i + 1, 0, Hp - 1)
            x1i = jnp.clip(x0i + 1, 0, Wp - 1)
            ok = (yy2 > -1) & (yy2 < Hp) & (xx2 > -1) & (xx2 < Wp)
            v = (img2d[y0i, x0i] * (1 - wy) * (1 - wx)
                 + img2d[y0i, x1i] * (1 - wy) * wx
                 + img2d[y1i, x0i] * wy * (1 - wx)
                 + img2d[y1i, x1i] * wy * wx)
            return jnp.where(ok, v, 0.0)

        cpg = C // deformable_groups  # channels per deformable group

        def per_n(n):
            def per_c(c):
                dg = c // cpg
                s = bilin(xp[n, c], sy[n, dg], sx[n, dg])  # [k, oh, ow]
                if mask_v is not None:
                    s = s * mask_v[n, dg]
                return s

            return jax.vmap(per_c)(jnp.arange(C))  # [C, k, oh, ow]

        cols = jax.vmap(per_n)(jnp.arange(N))  # [N, C, k, oh, ow]
        cols = cols.reshape(N, C * kh * kw, oh * ow)
        wmat = w.reshape(Co, Cg * kh * kw)
        if groups == 1:
            out = jnp.einsum("ok,nkp->nop", wmat, cols)
        else:
            cols_g = cols.reshape(N, groups, (C // groups) * kh * kw, oh * ow)
            wg = wmat.reshape(groups, Co // groups, Cg * kh * kw)
            out = jnp.einsum("gok,ngkp->ngop", wg, cols_g).reshape(
                N, Co, oh * ow)
        out = out.reshape(N, Co, oh, ow)
        if bias is not None:
            out = out + rest[-1].reshape(1, -1, 1, 1)
        return out

    ins = [xt, ot, wt]
    if mask is not None:
        ins.append(ensure_tensor(mask))
    if bias is not None:
        ins.append(ensure_tensor(bias))
    return apply_op(fn, ins, name="deform_conv2d")


class DeformConv2D:
    """reference: vision/ops.py DeformConv2D layer."""

    def __new__(cls, *a, **k):
        from .. import nn

        class _DeformConv2D(nn.Layer):
            def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                         padding=0, dilation=1, deformable_groups=1, groups=1,
                         weight_attr=None, bias_attr=None):
                super().__init__()
                ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
                    else tuple(kernel_size)
                self._attrs = dict(stride=stride, padding=padding,
                                   dilation=dilation,
                                   deformable_groups=deformable_groups,
                                   groups=groups)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, *ks], attr=weight_attr)
                self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                                  is_bias=True)

            def forward(self, x, offset, mask=None):
                return deform_conv2d(x, offset, self.weight, mask=mask,
                                     bias=self.bias, **self._attrs)

        return _DeformConv2D(*a, **k)


class RoIAlign:
    def __new__(cls, output_size, spatial_scale=1.0):
        from .. import nn

        class _RoIAlign(nn.Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_align(x, boxes, boxes_num, output_size, spatial_scale)

        return _RoIAlign()


class RoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from .. import nn

        class _RoIPool(nn.Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_pool(x, boxes, boxes_num, output_size, spatial_scale)

        return _RoIPool()
