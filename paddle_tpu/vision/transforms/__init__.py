"""paddle_tpu.vision.transforms (reference: python/paddle/vision/transforms/
— class transforms over numpy HWC images + functional API). Host-side numpy
only: transforms run in DataLoader workers and must never touch the device
backend (generator.host_rng pattern)."""
from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, affine, center_crop,
    crop, erase, hflip, normalize, perspective, pad, resize, rotate, to_grayscale, to_tensor, vflip,
)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomCrop,
    RandomErasing, RandomHorizontalFlip, RandomResizedCrop, RandomRotation,
    RandomVerticalFlip, Resize, SaturationTransform, ToTensor, Transpose,
)

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "Pad", "RandomRotation", "ColorJitter",
    "Grayscale", "BrightnessTransform", "ContrastTransform", "HueTransform",
    "SaturationTransform", "RandomErasing",
    "to_tensor", "resize", "crop", "center_crop", "hflip", "vflip",
    "normalize", "pad", "rotate", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_hue",
    "RandomAffine", "RandomPerspective", "affine",
    "perspective", "erase",
]

from .transforms import RandomAffine, RandomErasing, RandomPerspective  # noqa: F401,E402
