"""Functional image transforms over numpy arrays (reference:
python/paddle/vision/transforms/functional.py + functional_cv2.py; the
PIL/cv2 backends collapse to one numpy implementation — HWC uint8/float)."""
from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

__all__ = [
    "to_tensor", "resize", "crop", "center_crop", "hflip", "vflip",
    "normalize", "pad", "rotate", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_hue", "affine", "perspective", "erase",
]


def _as_hwc(img) -> np.ndarray:
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def to_tensor(pic, data_format: str = "CHW"):
    """HWC uint8 [0,255] -> float32 CHW [0,1] (reference: functional.to_tensor).
    Returns numpy (DataLoader collates to device arrays at the batch level)."""
    arr = _as_hwc(pic).astype("float32")
    if arr.max() > 1.0:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


def _interp_resize(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize in pure numpy (no cv2/PIL in the image)."""
    H, W = img.shape[:2]
    if (H, W) == (h, w):
        return img
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    im = img.astype("float32")
    out = (im[y0][:, x0] * (1 - wy) * (1 - wx) + im[y0][:, x1] * (1 - wy) * wx
           + im[y1][:, x0] * wy * (1 - wx) + im[y1][:, x1] * wy * wx)
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def resize(img, size, interpolation: str = "bilinear"):
    """reference: functional.resize — size int (short side) or (h, w)."""
    arr = _as_hwc(img)
    H, W = arr.shape[:2]
    if isinstance(size, int):
        if H < W:
            h, w = size, int(size * W / H)
        else:
            h, w = int(size * H / W), size
    else:
        h, w = size
    return _interp_resize(arr, int(h), int(w))


def crop(img, top: int, left: int, height: int, width: int):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _as_hwc(img)
    H, W = arr.shape[:2]
    th, tw = output_size
    return crop(arr, (H - th) // 2, (W - tw) // 2, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def normalize(img, mean, std, data_format: str = "CHW", to_rgb: bool = False):
    arr = np.asarray(img, dtype="float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        return (arr - mean[:, None, None]) / std[:, None, None]
    return (arr - mean) / std


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _as_hwc(img)
    if isinstance(padding, int):
        pl = pt = pr = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kw)


def rotate(img, angle: float, interpolation="nearest", expand=False,
           center=None, fill=0):
    """Nearest-neighbor rotation (reference: functional.rotate)."""
    arr = _as_hwc(img)
    H, W = arr.shape[:2]
    a = -np.deg2rad(angle)
    cy, cx = ((H - 1) / 2.0, (W - 1) / 2.0) if center is None else center
    yy, xx = np.mgrid[0:H, 0:W]
    ys = cy + (yy - cy) * np.cos(a) - (xx - cx) * np.sin(a)
    xs = cx + (yy - cy) * np.sin(a) + (xx - cx) * np.cos(a)
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
    out = np.full_like(arr, fill)
    out[ok] = arr[yi[ok], xi[ok]]
    return out


def to_grayscale(img, num_output_channels: int = 1):
    arr = _as_hwc(img).astype("float32")
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return gray.astype(np.asarray(img).dtype)


def adjust_brightness(img, brightness_factor: float):
    arr = _as_hwc(img).astype("float32") * brightness_factor
    return _clip_like(arr, img)


def adjust_contrast(img, contrast_factor: float):
    arr = _as_hwc(img).astype("float32")
    mean = to_grayscale(arr).mean()
    out = (arr - mean) * contrast_factor + mean
    return _clip_like(out, img)


def adjust_hue(img, hue_factor: float):
    """reference: functional.adjust_hue — rotate hue in HSV space."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _as_hwc(img).astype("float32")
    scale = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    x = arr / scale
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6.0
    h = (h + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    rgb = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], axis=-1)
    return _clip_like(rgb * scale, img)


def _clip_like(arr, ref):
    dt = np.asarray(ref).dtype
    if dt == np.uint8:
        return np.clip(arr, 0, 255).astype(np.uint8)
    return arr.astype("float32")


def _affine_sample(img: np.ndarray, matrix: np.ndarray,
                   interpolation: str = "nearest",
                   fill=0) -> np.ndarray:
    """Sample HWC image at inverse-affine-mapped coordinates (shared by
    affine/perspective/rotate-family transforms)."""
    H, W = img.shape[:2]
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float64),
                         np.arange(W, dtype=np.float64), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)
    src = matrix @ coords
    if matrix.shape[0] == 3:  # perspective: homogeneous divide
        src = src[:2] / np.maximum(np.abs(src[2:3]), 1e-9) * np.sign(
            src[2:3])
    sx, sy = src[0].reshape(H, W), src[1].reshape(H, W)
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = sx - x0
        wy = sy - y0
        out = np.zeros_like(img, dtype=np.float64)
        for dy in (0, 1):
            for dx in (0, 1):
                xi = np.clip(x0 + dx, 0, W - 1)
                yi = np.clip(y0 + dy, 0, H - 1)
                wgt = ((wx if dx else 1 - wx) * (wy if dy else 1 - wy))
                out += img[yi, xi].astype(np.float64) * wgt[..., None]
    else:
        xi = np.clip(np.round(sx).astype(np.int64), 0, W - 1)
        yi = np.clip(np.round(sy).astype(np.int64), 0, H - 1)
        out = img[yi, xi].astype(np.float64)
    oob = (sx < 0) | (sx > W - 1) | (sy < 0) | (sy > H - 1)
    out[oob] = fill
    return out.astype(img.dtype)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine transform (reference: functional.affine — rotate/translate/
    scale/shear about the center)."""
    arr = _as_hwc(img)
    H, W = arr.shape[:2]
    cx, cy = center if center is not None else ((W - 1) / 2, (H - 1) / 2)
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in (shear if isinstance(
        shear, (list, tuple)) else (shear, 0.0))]
    # forward matrix: T(center+translate) R(rot) Shear Scale T(-center)
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    M = np.array([[a * scale, b * scale,
                   cx + translate[0] - (a * scale) * cx - (b * scale) * cy],
                  [c * scale, d * scale,
                   cy + translate[1] - (c * scale) * cx - (d * scale) * cy]],
                 np.float64)
    # sample with the INVERSE mapping
    Mi = np.linalg.inv(np.vstack([M, [0, 0, 1]]))[:2]
    return _affine_sample(arr, Mi, interpolation, fill)


def _perspective_coeffs(startpoints, endpoints) -> np.ndarray:
    """Solve the 8-dof homography mapping endpoints -> startpoints."""
    A = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b += [sx, sy]
    coef = np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(b, np.float64))
    return np.vstack([coef[:6].reshape(2, 3),
                      [coef[6], coef[7], 1.0]])


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective transform (reference: functional.perspective)."""
    arr = _as_hwc(img)
    M = _perspective_coeffs(startpoints, endpoints)
    return _affine_sample(arr, M, interpolation, fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v (reference: functional.erase). Accepts
    HWC numpy or CHW tensors (erased in the layout given)."""
    from ...tensor import Tensor

    if isinstance(img, Tensor):
        import jax.numpy as jnp

        arr = img._value
        if not inplace:
            arr = jnp.asarray(arr)
        val = jnp.asarray(v, arr.dtype)
        out = arr.at[..., i:i + h, j:j + w].set(
            val if val.ndim == 0 else val)
        if inplace:
            img._set_value(out)
            return img
        return Tensor(out)
    arr = np.asarray(img) if inplace else np.array(img)
    arr[i:i + h, j:j + w] = v
    return arr
