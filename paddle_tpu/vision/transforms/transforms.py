"""Class-style transforms (reference: python/paddle/vision/transforms/
transforms.py — BaseTransform with _apply_image dispatch, Compose)."""
from __future__ import annotations

import numbers
import random as _random
from typing import Optional, Sequence

import numpy as np

from ...generator import host_rng
from . import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Normalize", "Transpose", "Pad", "RandomRotation", "ColorJitter",
    "Grayscale", "BrightnessTransform", "ContrastTransform", "HueTransform",
    "SaturationTransform", "RandomErasing",
    "RandomAffine", "RandomPerspective",
]


class BaseTransform:
    """reference: transforms.py BaseTransform — keys-based multi-input apply."""

    def __init__(self, keys: Optional[Sequence[str]] = None):
        self.keys = keys or ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            out = []
            for key, data in zip(self.keys, inputs):
                if key == "image":
                    out.append(self._apply_image(data))
                else:
                    out.append(data)
            return tuple(out) + tuple(inputs[len(self.keys):])
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    """reference: paddle.vision.transforms.Resize.

    Examples:
        >>> t = paddle.vision.transforms.Resize((8, 8))
        >>> img = np.zeros((16, 12, 3), "uint8")
        >>> t(img).shape
        (8, 8, 3)
    """

    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = np.asarray(img)
        th, tw = self.size
        H, W = arr.shape[:2]
        if self.pad_if_needed and (H < th or W < tw):
            img = F.pad(arr, (0, 0, max(tw - W, 0), max(th - H, 0)), self.fill,
                        self.padding_mode)
            arr = np.asarray(img)
            H, W = arr.shape[:2]
        rng = host_rng()
        top = int(rng.integers(0, H - th + 1))
        left = int(rng.integers(0, W - tw + 1))
        return F.crop(arr, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = np.asarray(img)
        H, W = arr.shape[:2]
        area = H * W
        rng = host_rng()
        for _ in range(10):
            target = area * rng.uniform(*self.scale)
            logr = np.log(self.ratio)
            ar = np.exp(rng.uniform(*logr))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                top = int(rng.integers(0, H - h + 1))
                left = int(rng.integers(0, W - w + 1))
                patch = F.crop(arr, top, left, h, w)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(H, W)), self.size,
                        self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if host_rng().random() < self.prob:
            return F.hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if host_rng().random() < self.prob:
            return F.vflip(img)
        return np.asarray(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.center, self.fill = center, fill

    def _apply_image(self, img):
        angle = host_rng().uniform(*self.degrees)
        return F.rotate(img, angle, center=self.center, fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = host_rng().uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = host_rng().uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        factor = host_rng().uniform(max(0, 1 - self.value), 1 + self.value)
        gray = F.to_grayscale(img, 3).astype("float32")
        arr = np.asarray(img).astype("float32")
        return F._clip_like(arr * factor + gray * (1 - factor), img)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return F.adjust_hue(img, host_rng().uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """reference: transforms.py ColorJitter — random order of the four."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = host_rng().permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomErasing(BaseTransform):
    """reference: transforms.py RandomErasing (CHW float input)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        arr = np.array(img, copy=True)
        rng = host_rng()
        if rng.random() >= self.prob:
            return arr
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        H, W = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = H * W
        for _ in range(10):
            target = area * rng.uniform(*self.scale)
            ar = np.exp(rng.uniform(*np.log(self.ratio)))
            h = int(round(np.sqrt(target / ar)))
            w = int(round(np.sqrt(target * ar)))
            if h < H and w < W:
                top = int(rng.integers(0, H - h + 1))
                left = int(rng.integers(0, W - w + 1))
                if chw:
                    arr[:, top:top + h, left:left + w] = self.value
                else:
                    arr[top:top + h, left:left + w] = self.value
                return arr
        return arr


class RandomAffine(BaseTransform):
    """Random affine transform (reference: transforms.py RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, (int, float)) else degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        rng = host_rng()  # paddle.seed-reproducible (module pattern)

        angle = rng.uniform(*self.degrees)
        h, w = np.asarray(img).shape[:2]
        if self.translate is not None:
            tx = rng.uniform(-self.translate[0], self.translate[0]) * w
            ty = rng.uniform(-self.translate[1], self.translate[1]) * h
            translate = (tx, ty)
        else:
            translate = (0.0, 0.0)
        scale = (rng.uniform(*self.scale) if self.scale is not None
                 else 1.0)
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, (int, float)):
                shear = (rng.uniform(-sh, sh), 0.0)
            elif len(sh) == 2:
                shear = (rng.uniform(sh[0], sh[1]), 0.0)
            else:
                shear = (rng.uniform(sh[0], sh[1]),
                         rng.uniform(sh[2], sh[3]))
        else:
            shear = (0.0, 0.0)
        return F.affine(img, angle, translate, scale, shear,
                        self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """Random perspective distortion (reference: transforms.py
    RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        rng = host_rng()

        if rng.random() >= self.prob:
            return np.asarray(img)
        h, w = np.asarray(img).shape[:2]
        d = self.distortion_scale
        hd = int(d * h / 2)
        wd = int(d * w / 2)
        ri = lambda hi: int(rng.integers(0, hi + 1))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(ri(wd), ri(hd)),
               (w - 1 - ri(wd), ri(hd)),
               (w - 1 - ri(wd), h - 1 - ri(hd)),
               (ri(wd), h - 1 - ri(hd))]
        return F.perspective(img, start, end, self.interpolation, self.fill)
