"""paddle_tpu.vision.datasets (reference: python/paddle/vision/datasets/ —
MNIST/FashionMNIST/Cifar10/Cifar100/Flowers/VOC2012 with download helpers).

Zero-egress build: no downloads. Each dataset reads the standard on-disk
format from a user-supplied path (``data_file``/``data_dir``); ``FakeData``
generates deterministic synthetic samples for pipelines and tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = [
    "MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData", "DatasetFolder", "ImageFolder", "Flowers", "VOC2012",
]


class MNIST(Dataset):
    """reference: datasets/mnist.py — idx-format images/labels."""

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        if download and (image_path is None or label_path is None):
            raise RuntimeError(
                f"{type(self).__name__}: downloads are disabled in this build; "
                "pass image_path/label_path to the local idx files")
        self.mode = mode
        self.transform = transform
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _parse_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _parse_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: datasets/cifar.py — the python-pickle tar format."""

    _num_classes = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        if data_file is None:
            raise RuntimeError(
                f"{type(self).__name__}: downloads are disabled in this build; "
                "pass data_file to the local cifar tar.gz")
        self.mode = mode
        self.transform = transform
        self.data, self.labels = self._load(data_file)

    def _member_filter(self, name: str) -> bool:
        if self._num_classes == 10:
            return ("data_batch" in name) if self.mode == "train" else (
                "test_batch" in name)
        return name.endswith("train") if self.mode == "train" else name.endswith("test")

    def _load(self, data_file):
        datas, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in sorted(tf.getmembers(), key=lambda m: m.name):
                if not member.isfile() or not self._member_filter(member.name):
                    continue
                batch = pickle.load(tf.extractfile(member), encoding="bytes")
                datas.append(batch[b"data"])
                labels.extend(batch.get(b"labels", batch.get(b"fine_labels", [])))
        data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, dtype="int64")

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _num_classes = 100


class FakeData(Dataset):
    """Deterministic synthetic dataset (pipelines/tests/benchmarks without
    real data — stands in for the reference's downloadable sets)."""

    def __init__(self, size: int = 1000, image_shape=(3, 224, 224),
                 num_classes: int = 1000, transform: Optional[Callable] = None,
                 dtype: str = "float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __getitem__(self, idx):
        if idx < 0 or idx >= self.size:
            raise IndexError(idx)
        rng = np.random.default_rng(idx)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return self.size


def _scan_files(root, exts, is_valid_file):
    """Walk ``root`` collecting files by extension/validator (shared by
    DatasetFolder and ImageFolder)."""
    import os

    found = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fname in sorted(files):
            path = os.path.join(dirpath, fname)
            ok = (is_valid_file(path) if is_valid_file
                  else fname.lower().endswith(exts))
            if ok:
                found.append(path)
    return found


_IMG_EXTS = (".npy", ".jpg", ".jpeg", ".png", ".bmp")


class DatasetFolder(Dataset):
    """Samples arranged class-per-directory (reference:
    vision/datasets/folder.py DatasetFolder). Default loader reads .npy
    arrays (no PIL dependency in this image); pass ``loader`` for other
    formats."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(extensions) if extensions else _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class directories under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = [
            (path, self.class_to_idx[c])
            for c in classes
            for path in _scan_files(os.path.join(root, c), exts,
                                    is_valid_file)]
        if not self.samples:
            raise RuntimeError(f"no valid sample files under {root!r}")

    @staticmethod
    def _default_loader(path):
        import numpy as _np

        if path.endswith(".npy"):
            return _np.load(path)
        from ..image import image_load

        return image_load(path)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(DatasetFolder):
    """Flat image folder without labels (reference: folder.py
    ImageFolder): __getitem__ returns [sample]."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(extensions) if extensions else _IMG_EXTS
        self.samples = _scan_files(root, exts, is_valid_file)
        if not self.samples:
            raise RuntimeError(f"no valid sample files under {root!r}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


class Flowers(Dataset):
    """reference: vision/datasets/flowers.py — download-based corpus;
    zero-egress: local cache or a clear error (same contract as MNIST
    above)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        raise RuntimeError(
            "Flowers downloads its corpus from the network; this "
            "environment is zero-egress. Arrange the images locally and "
            "use DatasetFolder instead.")


class VOC2012(Dataset):
    """reference: vision/datasets/voc2012.py — same zero-egress contract."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        raise RuntimeError(
            "VOC2012 downloads its corpus from the network; this "
            "environment is zero-egress. Arrange the images locally and "
            "use DatasetFolder instead.")
