"""paddle_tpu.vision.datasets (reference: python/paddle/vision/datasets/ —
MNIST/FashionMNIST/Cifar10/Cifar100/Flowers/VOC2012 with download helpers).

Zero-egress build: no downloads. Each dataset reads the standard on-disk
format from a user-supplied path (``data_file``/``data_dir``); ``FakeData``
generates deterministic synthetic samples for pipelines and tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class MNIST(Dataset):
    """reference: datasets/mnist.py — idx-format images/labels."""

    NAME = "mnist"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        if download and (image_path is None or label_path is None):
            raise RuntimeError(
                f"{type(self).__name__}: downloads are disabled in this build; "
                "pass image_path/label_path to the local idx files")
        self.mode = mode
        self.transform = transform
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _parse_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _parse_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: datasets/cifar.py — the python-pickle tar format."""

    _num_classes = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        if data_file is None:
            raise RuntimeError(
                f"{type(self).__name__}: downloads are disabled in this build; "
                "pass data_file to the local cifar tar.gz")
        self.mode = mode
        self.transform = transform
        self.data, self.labels = self._load(data_file)

    def _member_filter(self, name: str) -> bool:
        if self._num_classes == 10:
            return ("data_batch" in name) if self.mode == "train" else (
                "test_batch" in name)
        return name.endswith("train") if self.mode == "train" else name.endswith("test")

    def _load(self, data_file):
        datas, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in sorted(tf.getmembers(), key=lambda m: m.name):
                if not member.isfile() or not self._member_filter(member.name):
                    continue
                batch = pickle.load(tf.extractfile(member), encoding="bytes")
                datas.append(batch[b"data"])
                labels.extend(batch.get(b"labels", batch.get(b"fine_labels", [])))
        data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, dtype="int64")

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        label = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _num_classes = 100


class FakeData(Dataset):
    """Deterministic synthetic dataset (pipelines/tests/benchmarks without
    real data — stands in for the reference's downloadable sets)."""

    def __init__(self, size: int = 1000, image_shape=(3, 224, 224),
                 num_classes: int = 1000, transform: Optional[Callable] = None,
                 dtype: str = "float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __getitem__(self, idx):
        if idx < 0 or idx >= self.size:
            raise IndexError(idx)
        rng = np.random.default_rng(idx)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return self.size
