"""Image backend selection + loading (reference: vision/image.py).

Backends: 'pil' (default; requires Pillow) and 'cv2' (requires OpenCV).
Neither is guaranteed in this image — backends import lazily and raise
a clear error when absent; 'tensor'-style numpy loading always works
for .npy files.
"""
from __future__ import annotations

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_backend = "pil"


def set_image_backend(backend: str) -> None:
    global _backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    _backend = backend


def get_image_backend() -> str:
    return _backend


def image_load(path: str, backend=None):
    """Load an image with the selected backend (PIL Image or cv2 ndarray);
    .npy arrays load regardless of backend availability."""
    b = backend or _backend
    if path.endswith(".npy"):
        import numpy as np

        return np.load(path)
    if b == "pil":
        try:
            from PIL import Image
        except ImportError as e:
            raise ImportError(
                "image_load backend 'pil' needs Pillow; this image has no "
                "network egress to install it — use .npy inputs or cv2"
            ) from e
        return Image.open(path)
    try:
        import cv2
    except ImportError as e:
        raise ImportError("image_load backend 'cv2' needs OpenCV") from e
    return cv2.imread(path)
