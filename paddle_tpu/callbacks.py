"""paddle.callbacks facade (reference: python/paddle/callbacks.py —
re-exports the hapi callbacks; same 8-name __all__)."""
from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
    WandbCallback,
)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau",
           "WandbCallback"]
