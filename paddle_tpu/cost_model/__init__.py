"""paddle.cost_model — measure/estimate op and program costs.

Reference parity: ``python/paddle/cost_model/cost_model.py`` (CostModel:
``profile_measure`` runs the program under the profiler and returns
per-op time + the static op-benchmark table). TPU redesign: the cost
oracle is XLA itself — ``profile_measure`` compiles the program and
reads the compiler's cost analysis (flops, bytes accessed, estimated
seconds when available), plus a wall-clock measurement of one real run.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

__all__ = ["CostModel"]


class CostModel:
    def profile_measure(self, main_program=None, startup_program=None,
                        device: str = "tpu",
                        fetch_cost_list: Sequence[str] = ("time",),
                        fn=None, example_args: tuple = ()) -> dict:
        """Cost of one program execution.

        Two entry forms: the reference's (static ``main_program`` built
        under ``static.program_guard``) or a direct jittable ``fn`` +
        ``example_args``.
        Returns {"flops", "bytes_accessed", "wall_time_ms", ...}.
        """
        import jax

        if fn is None:
            if main_program is None:
                raise ValueError("profile_measure needs main_program or fn")
            from .. import static as _static

            exe = _static.Executor()
            if startup_program is not None:
                exe.run(startup_program)
            t0 = time.time()
            exe.run(main_program)
            wall_ms = (time.time() - t0) * 1000.0
            cost = {"wall_time_ms": wall_ms}
            analysis = getattr(main_program, "_cost_analysis", None)
            if callable(analysis):
                cost.update(analysis() or {})
            return cost

        jitted = jax.jit(fn)
        lowered = jitted.lower(*example_args)
        compiled = lowered.compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0] if analysis else {}
        t0 = time.time()
        out = jitted(*example_args)
        jax.block_until_ready(out)
        wall_ms = (time.time() - t0) * 1000.0
        return {
            "flops": int(analysis.get("flops", 0)),
            "bytes_accessed": int(analysis.get("bytes accessed", 0)),
            "wall_time_ms": wall_ms,
            "device": jax.devices()[0].platform,
        }

    def static_cost_data(self) -> dict:
        """The reference loads a pre-benchmarked op-cost table here; on
        TPU the compiler's analysis replaces static tables."""
        return {}
