"""paddle_tpu.analysis — tpulint, the repo's static invariant linter.

Runtime drills prove the stack's invariants one scenario at a time;
this package proves the *code shape* that makes those drills
meaningful, on every file, at lint time:

- **TPL001** no host sync (``.item()`` / ``float()`` / ``np.asarray`` /
  ``device_get``) inside a compiled scope — the one-fetch discipline.
- **TPL002** no retrace hazards: Python branches / f-strings over
  traced values, time- or random-derived scalars into compiled
  callables — "decode compiles exactly once" as a lint property.
- **TPL003** metric-catalog parity with docs/OBSERVABILITY.md, both
  directions, plus label-set consistency across ``.labels()`` sites.
- **TPL004** fault-point parity with docs/RESILIENCE.md, both ways.
- **TPL005** no unseeded randomness under serving/faults/checkpoint —
  the (prompt, seed) determinism contract.
- **TPL006** declared shared containers mutate only under their lock.
- **TPL007** the declared-lock acquisition graph is acyclic: a cycle
  is a deadlock hazard, reported with every edge's witness path.
- **TPL008** no check-then-act across a lock release: a guarded read
  must not feed a guarded write in a different ``with`` of the same
  lock (``# tpulint: atomic-ok`` opts out).
- **TPL009** no blocking/unbounded work (file I/O, restore, compile
  builds, sleeps, socket ops, thread joins, engine ``step``) reached
  while a declared lock is held.

CLI: ``python tools/tpulint.py paddle_tpu tools examples`` (add
``--json`` for CI-diffable output, ``--lock-graph`` for the DOT
acquisition graph). Suppress one site with
``# tpulint: disable=TPL00N``; accept a pre-existing finding in
``tools/tpulint_baseline.json``. Full catalog: docs/ANALYSIS.md.

Stdlib-only and importable WITHOUT jax or the rest of paddle_tpu —
``tools/tpulint.py`` loads it standalone so the linter can gate a
commit that breaks the package import itself.
"""
from .catalog import (parse_fault_doc, parse_metric_doc,
                      sanitize_metric_name)
from .core import (Finding, LintConfig, LintResult, ModuleInfo, Project,
                   iter_py_files, lint_paths, load_baseline, parse_module,
                   split_baseline, to_json, to_text, write_baseline)
from .locks import LockWorld, lock_graph_dot, module_lock_decls
from .rules import FILE_RULES, PROJECT_RULES, RULE_IDS, lock_graph_for

__all__ = [
    "FILE_RULES", "Finding", "LintConfig", "LintResult", "LockWorld",
    "ModuleInfo", "PROJECT_RULES", "Project", "RULE_IDS", "iter_py_files",
    "lint_paths", "load_baseline", "lock_graph_dot", "lock_graph_for",
    "module_lock_decls", "parse_fault_doc", "parse_metric_doc",
    "parse_module", "sanitize_metric_name", "split_baseline", "to_json",
    "to_text", "write_baseline",
]
