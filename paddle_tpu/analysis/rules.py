"""The tpulint rule set — each rule guards one runtime invariant.

| rule | invariant it guards | introduced by |
|---|---|---|
| TPL001 | no host sync inside a compiled scope | PR 1/9 one-fetch discipline |
| TPL002 | decode/prefill compile once (no retrace hazards) | PR 1 |
| TPL003 | metric catalog == docs/OBSERVABILITY.md, both ways | PR 2 |
| TPL004 | fault-point catalog == docs/RESILIENCE.md, both ways | PR 3 |
| TPL005 | sampling is a pure function of (prompt, seed) | PR 7 |
| TPL006 | shared registry/router state mutates under its lock | PR 2/5 |
| TPL007 | the lock-acquisition graph is acyclic (no deadlock) | PR 13 |
| TPL008 | check-then-act stays inside ONE critical section | PR 13 |
| TPL009 | no blocking/unbounded work while a lock is held | PR 13 |
| TPL010 | trace-event catalog == docs/OBSERVABILITY.md, both ways | PR 17 |

Every rule is syntactic (per-module AST, no import resolution) and errs
toward silence: a miss is caught by the runtime drills these rules
summarize; a false positive trains people to sprinkle suppressions.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .catalog import (FaultSite, MetricRegistration, TraceEmit,
                      collect_fault_sites, collect_label_uses,
                      collect_metric_registrations, collect_trace_emits,
                      parse_event_doc, parse_fault_doc, parse_metric_doc,
                      registration_of)
from .core import Finding, LintConfig, ModuleInfo, Project
from .locks import LockWorld, module_lock_decls
from .scopes import CompiledScopes, Taint, dotted_name

__all__ = ["FILE_RULES", "PROJECT_RULES", "RULE_IDS"]


def _jax_random_aliases(tree: ast.Module) -> Set[str]:
    """Names the module bound to jax.random (`from jax import random`,
    `import jax.random as jrandom`): their draws are key-threaded and
    pure — TPL005's stdlib branch and TPL002's varying-scalar call-site
    scan must both leave them alone."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name == "random":
                    out.add(alias.asname or "random")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.random" and alias.asname:
                    out.add(alias.asname)
    return out


def _time_seed_of(call: ast.Call) -> Optional[str]:
    """The dotted name of a wall-clock/entropy source called anywhere
    inside ``call``'s arguments, or None."""
    for sub in ast.walk(call):
        if isinstance(sub, ast.Call) and sub is not call:
            src = dotted_name(sub.func) or ""
            if src in _TIME_SOURCES:
                return src
    return None


def _in_scope(relpath: str, scope: str) -> bool:
    """Path-boundary-aware prefix test: scope "paddle_tpu/serving"
    covers the dir and its contents but NOT a sibling like
    paddle_tpu/serving_utils.py. Empty scope covers everything
    (fixtures widen to ("",))."""
    if not scope:
        return True
    scope = scope.rstrip("/")
    return relpath == scope or relpath.startswith(scope + "/")


def _scopes(module: ModuleInfo) -> CompiledScopes:
    cached = getattr(module, "_compiled_scopes", None)
    if cached is None:
        cached = CompiledScopes(module.tree)
        module._compiled_scopes = cached
    return cached


def _taint(module: ModuleInfo, fn) -> Taint:
    """One Taint pass per (module, compiled fn) — TPL001 and TPL002
    both consume it; building it twice would double the forward pass
    and let the two rules drift apart on a future taint fix."""
    cache = getattr(module, "_taint_cache", None)
    if cache is None:
        cache = {}
        module._taint_cache = cache
    taint = cache.get(fn)
    if taint is None:
        taint = cache[fn] = Taint(fn)
    return taint


def _compiled_roots(scopes: CompiledScopes):
    """Compiled fns not lexically covered by a compiled ancestor's walk
    — by POSITION, not by mark reason: a decorated def nested inside a
    compiled fn keeps its 'decorated' reason but must still not be
    walked twice (one defect, one finding)."""
    nested: Set[ast.AST] = set()
    for fn in scopes.compiled:
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.add(sub)
    for fn, reason in scopes.compiled.items():
        if fn not in nested:
            yield fn, reason


_SYNC_METHODS = {"item", "numpy", "tolist", "block_until_ready"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_NP_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array"}


class TPL001HostSyncInCompiled:
    """``.item()`` / ``float()`` / ``np.asarray`` / ``device_get`` on a
    traced value inside a compiled scope. Each is a device→host fetch:
    under trace it either raises (ConcretizationError) or — worse —
    silently bakes one concrete value into the compiled program. The
    compiled step's contract is ONE fetch, owned by the host caller."""

    id = "TPL001"

    def check(self, module: ModuleInfo, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        scopes = _scopes(module)
        for fn, _reason in _compiled_roots(scopes):
            taint = _taint(module, fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _SYNC_METHODS
                        and taint.is_traced(func.value)):
                    out.append(Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f"host sync `.{func.attr}()` on a traced value "
                        f"inside compiled fn `{fn.name}`"))
                elif (isinstance(func, ast.Name)
                        and func.id in _CAST_BUILTINS and node.args
                        and taint.is_traced(node.args[0])):
                    out.append(Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f"`{func.id}()` forces a traced value to host "
                        f"inside compiled fn `{fn.name}`"))
                else:
                    name = dotted_name(func) or ""
                    if (name in _NP_MATERIALIZERS and node.args
                            and taint.is_traced(node.args[0])):
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"`{name}()` materializes a traced value on "
                            f"host inside compiled fn `{fn.name}`"))
                    elif name.split(".")[-1] == "device_get":
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"`{name}()` inside compiled fn `{fn.name}` "
                            f"— device fetch has no place under trace"))
        return out


_TIME_CALLS = {"time.time", "time.perf_counter", "time.time_ns",
               "time.monotonic", "datetime.now", "datetime.datetime.now"}


def _has_varying_host_scalar(arg: ast.AST,
                             jax_random_names: Set[str] = frozenset()
                             ) -> Optional[str]:
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func) or ""
            if name in _TIME_CALLS:
                return f"`{name}()`"
            if (name.startswith(("random.", "np.random.",
                                 "numpy.random."))
                    and name.split(".", 1)[0] not in jax_random_names):
                return f"`{name}()`"
        if isinstance(sub, ast.JoinedStr) and _fstring_varies(sub):
            return "an f-string"
    return None


def _fstring_varies(node: ast.JoinedStr) -> bool:
    """True when the f-string can take a different value between calls.
    Literal text and ALL_CAPS module constants (`f"v{VERSION}"`) format
    to the same string every call — one signature, one compile — and
    must not fire."""
    for fv in node.values:
        if not isinstance(fv, ast.FormattedValue):
            continue
        expr = fv.value
        if isinstance(expr, ast.Constant):
            continue
        if isinstance(expr, ast.Name) and expr.id.isupper():
            continue
        return True
    return False


class TPL002RecompileHazard:
    """Inside a compiled scope: Python control flow on traced values
    (retrace per branch — or a ConcretizationError at first trace) and
    string conversion of traced values (f-string / ``str()`` — host
    sync dressed as formatting). At call sites of compiled callables:
    time/random-derived scalars passed as arguments — every distinct
    value is a new signature, i.e. a recompile per step (the 138 s
    compile in BENCH_r05 makes that a production outage, not a
    slowdown)."""

    id = "TPL002"

    def check(self, module: ModuleInfo, config: LintConfig) -> List[Finding]:
        out: List[Finding] = []
        scopes = _scopes(module)
        for fn, _reason in _compiled_roots(scopes):
            taint = _taint(module, fn)
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    if taint.is_traced(node.test):
                        kw = "while" if isinstance(node, ast.While) else "if"
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"Python `{kw}` on a traced value inside "
                            f"compiled fn `{fn.name}` — use jnp.where/"
                            f"lax.cond (retrace or ConcretizationError)"))
                elif isinstance(node, ast.IfExp):
                    if taint.is_traced(node.test):
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"conditional expression on a traced value "
                            f"inside compiled fn `{fn.name}` — use "
                            f"jnp.where"))
                elif isinstance(node, ast.Assert):
                    if taint.is_traced(node.test):
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"assert on a traced value inside compiled "
                            f"fn `{fn.name}` — use checkify or a host-"
                            f"side flag output"))
                elif isinstance(node, ast.JoinedStr):
                    if taint.is_traced(node):
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"f-string over a traced value inside "
                            f"compiled fn `{fn.name}` — host sync "
                            f"dressed as formatting"))
                elif isinstance(node, ast.Call):
                    func = node.func
                    if (isinstance(func, ast.Name)
                            and func.id in ("str", "repr", "format")
                            and node.args
                            and taint.is_traced(node.args[0])):
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"`{func.id}()` of a traced value inside "
                            f"compiled fn `{fn.name}`"))
                elif isinstance(node, ast.For):
                    it = node.iter
                    if (isinstance(it, ast.Call)
                            and isinstance(it.func, ast.Name)
                            and it.func.id == "range"
                            and any(taint.is_traced(a) for a in it.args)):
                        out.append(Finding(
                            self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"`range()` over a traced value inside "
                            f"compiled fn `{fn.name}` — use lax.scan/"
                            f"fori_loop"))
        # call-site half: varying host scalars into compiled callables
        jax_random_names = _jax_random_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None or callee not in scopes.compiled_bindings:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                varying = _has_varying_host_scalar(arg, jax_random_names)
                if varying is not None:
                    out.append(Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f"{varying} passed into compiled callable "
                        f"`{callee}` — every distinct value compiles a "
                        f"new program"))
        return out


class TPL003MetricCatalogParity:
    """Every registered metric family is documented in
    docs/OBSERVABILITY.md and every documented family is registered —
    plus label-set consistency: two registrations of one name must
    declare the same labels, and every ``.labels(...)`` call must use
    the declared set. The hand-synced table stops being hand-synced."""

    id = "TPL003"

    def check_project(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        config = project.config
        regs: List[MetricRegistration] = []
        for mod in project.modules:
            regs.extend(collect_metric_registrations(mod.tree, mod.relpath))

        # -- same-name registrations must agree on labels ------------------
        by_name: Dict[str, List[MetricRegistration]] = {}
        for r in regs:
            if r.name is not None:
                by_name.setdefault(r.name, []).append(r)
        for name, rlist in sorted(by_name.items()):
            label_sets = {r.labels for r in rlist if r.labels is not None}
            if len(label_sets) > 1:
                canonical = sorted(label_sets)[0]
                for r in rlist:
                    if r.labels is not None and r.labels != canonical:
                        out.append(Finding(
                            self.id, r.relpath, r.line, 0,
                            f"metric `{name}` registered with conflicting "
                            f"label sets {sorted(map(list, label_sets))} — "
                            f"one family, one label set"))

        # -- docs parity, both directions ----------------------------------
        doc_path = config.observability_doc
        doc_rel = os.path.relpath(doc_path, config.root).replace(os.sep, "/")
        if not os.path.isfile(doc_path):
            out.append(Finding(self.id, doc_rel, 1, 0,
                               "observability catalog doc not found"))
            return out
        documented = parse_metric_doc(doc_path)
        registered_names = set(by_name)
        for name, rlist in sorted(by_name.items()):
            first = min(rlist, key=lambda r: (r.relpath, r.line))
            if not _in_scope(first.relpath, config.metric_doc_scope):
                continue
            if name not in documented:
                out.append(Finding(
                    self.id, first.relpath, first.line, 0,
                    f"metric `{name}` is registered but not documented "
                    f"in {doc_rel}"))
        if project.full_scope:
            # docs→code only when the run covers the registration
            # universe — on a targeted lint the sites simply aren't in
            # the subset
            for name, (lineno, _labels) in sorted(documented.items()):
                if name not in registered_names:
                    out.append(Finding(
                        self.id, doc_rel, lineno, 0,
                        f"documented metric `{name}` has no registration "
                        f"site in the linted code"))

        # -- .labels() call sites vs declared label sets -------------------
        for mod in project.modules:
            out.extend(self._check_label_uses(mod))
        return out

    def _check_label_uses(self, mod: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        # receiver name -> [(line, metric name, declared labels or
        # None=unknown)] sorted by line: a rebound receiver validates
        # each .labels() call against the binding LIVE at that line,
        # not whichever assignment ast.walk happened to visit last
        bindings: Dict[str, List[Tuple[int, str,
                                       Optional[Tuple[str, ...]]]]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = dotted_name(node.targets[0])
            if target is None:
                continue
            value = node.value
            reg = (registration_of(value, mod.relpath)
                   if isinstance(value, ast.Call) else None)
            if reg is not None and reg.name is not None:
                bindings.setdefault(target, []).append(
                    (node.lineno, reg.name, reg.labels))
            elif (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr == "labels"
                    and isinstance(value.func.value, ast.Call)):
                # var = reg.histogram(...).labels(...): validate the
                # chained labels() below; the var binds a CHILD, which
                # takes no further .labels() calls
                pass
        for blist in bindings.values():
            blist.sort()
        for call, recv in collect_label_uses(mod.tree):
            declared: Optional[Tuple[str, ...]] = None
            name = None
            if recv is not None:
                for line, bname, blabels in bindings.get(recv, ()):
                    if line > call.lineno:
                        break
                    name, declared = bname, blabels
            elif isinstance(call.func.value, ast.Call):
                # chained reg.counter(...).labels(...) one-liner
                reg = registration_of(call.func.value, mod.relpath)
                if reg is not None:
                    name, declared = reg.name, reg.labels
            if declared is None:
                continue                    # unknown receiver or labels
            has_star = any(kw.arg is None for kw in call.keywords)
            kw_names = {kw.arg for kw in call.keywords if kw.arg}
            extra = kw_names - set(declared)
            if extra:
                out.append(Finding(
                    self.id, mod.relpath, call.lineno, 0,
                    f"labels({', '.join(sorted(extra))}=...) not in the "
                    f"declared label set {list(declared)} of metric "
                    f"`{name or '?'}`"))
            elif (not has_star and not call.args
                    and kw_names != set(declared)):
                missing = sorted(set(declared) - kw_names)
                out.append(Finding(
                    self.id, mod.relpath, call.lineno, 0,
                    f"labels(...) missing declared label(s) "
                    f"{missing} of metric `{name or '?'}`"))
            elif call.args and not call.keywords and len(call.args) != len(
                    declared):
                out.append(Finding(
                    self.id, mod.relpath, call.lineno, 0,
                    f"labels(...) takes {len(call.args)} positional "
                    f"value(s); metric `{name or '?'}` declares "
                    f"{len(declared)}"))
        return out


class TPL004FaultPointParity:
    """Every fault point named in code (``faults.point`` /
    ``declare_point`` / ``inject``) appears in the docs/RESILIENCE.md
    catalog table, and every cataloged point exists in code. A drill
    that arms a point nobody fires — or a point no drill documents —
    is resilience theater."""

    id = "TPL004"

    def check_project(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        config = project.config
        sites: List[FaultSite] = []
        for mod in project.modules:
            sites.extend(collect_fault_sites(mod.tree, mod.relpath))
        doc_path = config.resilience_doc
        doc_rel = os.path.relpath(doc_path, config.root).replace(os.sep, "/")
        if not os.path.isfile(doc_path):
            out.append(Finding(self.id, doc_rel, 1, 0,
                               "resilience catalog doc not found"))
            return out
        documented = parse_fault_doc(doc_path)
        by_name: Dict[str, List[FaultSite]] = {}
        for s in sites:
            by_name.setdefault(s.name, []).append(s)
        for name, slist in sorted(by_name.items()):
            if name not in documented:
                first = min(slist, key=lambda s: (s.relpath, s.line))
                out.append(Finding(
                    self.id, first.relpath, first.line, 0,
                    f"fault point `{name}` is not cataloged in "
                    f"{doc_rel}"))
        if project.full_scope:
            # docs→code direction: full-scope runs only (see TPL003)
            for name, lineno in sorted(documented.items()):
                if name not in by_name:
                    out.append(Finding(
                        self.id, doc_rel, lineno, 0,
                        f"cataloged fault point `{name}` has no "
                        f"point/declare_point/inject site in the linted "
                        f"code"))
        return out


_UNSEEDED_RANDOM = {
    "random", "randint", "uniform", "choice", "choices", "shuffle",
    "sample", "randrange", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "getrandbits", "randbytes",
}
_NP_SEEDED_OK = {"Generator", "SeedSequence", "BitGenerator"}
# constructors that are fine WITH a seed argument and entropy-seeded
# (nondeterministic) without one — `Generator(PCG64(seed))` is the very
# idiom the rule's message recommends
_NP_SEEDED_CTORS = {"default_rng", "RandomState", "PCG64", "PCG64DXSM",
                    "Philox", "MT19937", "SFC64"}
_TIME_SOURCES = {"time.time", "time.time_ns", "time.perf_counter",
                 "time.monotonic", "datetime.now", "datetime.datetime.now",
                 "os.urandom", "uuid.uuid4"}


class TPL005UnseededRandomness:
    """Unseeded randomness under serving/faults/checkpoint. PR 7 made a
    request's token stream a pure function of (prompt, seed) — that
    contract (and every bit-identical chaos drill riding it) dies the
    day someone reaches for the global RNG or a wall-clock PRNGKey."""

    id = "TPL005"

    def check(self, module: ModuleInfo, config: LintConfig) -> List[Finding]:
        if not any(_in_scope(module.relpath, scope)
                   for scope in config.tpl005_scopes):
            return []
        out: List[Finding] = []
        jax_random_names = _jax_random_aliases(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            parts = name.split(".")
            # PRNGKey first: under `from jax import random` its dotted
            # name starts with "random." and would fall into (and out
            # of) the stdlib-random branch below without ever reaching
            # the time-source scan
            if parts[-1] == "PRNGKey" or name.endswith("random.key"):
                src = _time_seed_of(node)
                if src is not None:
                    out.append(Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f"time-derived PRNGKey (`{src}()`) — "
                        f"sampling must be a pure function of "
                        f"(prompt, seed)"))
            elif name.startswith("random.") and "random" not in \
                    jax_random_names:
                fn = parts[-1]
                if fn in _UNSEEDED_RANDOM:
                    out.append(Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f"`{name}()` uses the process-global RNG — "
                        f"derive from a seeded random.Random or an "
                        f"injected generator"))
                elif fn == "Random":
                    out.extend(self._seed_findings(
                        module, node, "random.Random"))
            elif (name.startswith("np.random.")
                    or name.startswith("numpy.random.")):
                fn = parts[-1]
                if fn in _NP_SEEDED_CTORS:
                    out.extend(self._seed_findings(module, node, fn))
                elif fn not in _NP_SEEDED_OK:
                    out.append(Finding(
                        self.id, module.relpath, node.lineno,
                        node.col_offset,
                        f"`{name}()` uses numpy's global RNG — use an "
                        f"injected np.random.Generator"))
        return out

    def _seed_findings(self, module: ModuleInfo, node: ast.Call,
                       label: str) -> List[Finding]:
        """A seedable ctor must have a seed, and the seed must not be
        wall-clock: `default_rng(time.time_ns())` is the unseeded
        defect wearing an argument."""
        if not node.args and not node.keywords:
            return [Finding(self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"`{label}()` without a seed — pass one")]
        src = _time_seed_of(node)
        if src is not None:
            return [Finding(self.id, module.relpath, node.lineno,
                            node.col_offset,
                            f"`{label}()` seeded from `{src}()` — "
                            f"time-seeded is unseeded; sampling must "
                            f"be a pure function of (prompt, seed)")]
        return []


# attr (as written at the mutation site) -> required lock expr, per file.
# The table states the LOCKING CONTRACT each file already documents;
# new shared state opts in with a trailing
# ``# tpulint: guard=self._lock`` on its initialization line.
_LOCK_TABLE: Dict[str, Tuple[Tuple[str, str], ...]] = {
    "paddle_tpu/metrics/registry.py": (
        ("self._metrics", "self._lock"),
        ("self._children", "self._lock"),
    ),
    "paddle_tpu/faults/injection.py": (
        ("_active", "_lock"),
        ("_catalog", "_lock"),
    ),
    "paddle_tpu/checkpoint/manager.py": (
        ("_LIVE_TMP", "_LIVE_TMP_LOCK"),
    ),
    "paddle_tpu/serving/router.py": (
        ("self._models", "self._lock"),
        ("self._handles", "self._lock"),
        ("self._rr", "self._lock"),
    ),
    "paddle_tpu/metrics/server.py": (
        ("self._cb_engine_probe", "self._probe_lock"),
    ),
    "paddle_tpu/faults/watchdog.py": (
        ("self._in_step_since", "self._lock"),
        ("self._tripped", "self._lock"),
        ("self._healthy_streak", "self._lock"),
        ("self._trips", "self._lock"),
    ),
    "paddle_tpu/serving/api.py": (
        ("self._rr_idx", "self._rr_lock"),
    ),
    "paddle_tpu/distributed/checkpoint/__init__.py": (
        ("_pending", "_pending_lock"),
    ),
}

_MUTATORS = {"append", "add", "remove", "discard", "clear", "pop",
             "popitem", "update", "setdefault", "extend", "insert"}
_GUARD_RE = re.compile(r"#\s*tpulint:\s*guard=(\S+)")
_ATOMIC_OK_RE = re.compile(r"#\s*tpulint:\s*atomic-ok")


def _guard_map(module: ModuleInfo) -> Dict[str, str]:
    """attr -> lock expr for one module: the _LOCK_TABLE rows plus
    ``# tpulint: guard=<lock>`` birth-line annotations. Cached — TPL006,
    TPL008, and the LockWorld seed all consume it."""
    cached = getattr(module, "_guard_map_cache", None)
    if cached is None:
        cached = dict(_LOCK_TABLE.get(module.relpath, ()))
        cached.update(_annotated_guards(module))
        module._guard_map_cache = cached
    return cached


def _annotated_guards(module: ModuleInfo) -> Dict[str, str]:
    """``self._foo = {}  # tpulint: guard=self._lock`` declares the
    guard at the attr's birth line."""
    lines_with_guard: Dict[int, str] = {}
    for i, line in enumerate(module.lines, 1):
        m = _GUARD_RE.search(line)
        if m:
            lines_with_guard[i] = m.group(1)
    if not lines_with_guard:
        return {}
    found: Dict[str, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        lock = lines_with_guard.get(node.lineno)
        if lock is None:
            continue
        for t in targets:
            name = dotted_name(t)
            if name:
                found[name] = lock
    return found


def _lock_world(project: Project) -> LockWorld:
    """One LockWorld per lint run (TPL007 and TPL009 share the
    interprocedural pass — building it twice would double the fixpoint
    and let the two rules drift on a future resolution fix)."""
    world = getattr(project, "_lock_world", None)
    if world is None:
        world = LockWorld(
            project,
            guard_locks_of=lambda m: tuple(sorted(set(_guard_map(m)
                                                      .values()))))
        project._lock_world = world
    return world


class TPL006LockDiscipline:
    """Mutations of declared shared containers must happen inside
    ``with <their lock>:``. Driven by a small annotation table (above)
    plus in-source ``# tpulint: guard=<lock>`` annotations, so new
    shared state declares its lock where it is born. Reads are free —
    the repo's convention is copy-under-lock, read-outside."""

    id = "TPL006"

    def check(self, module: ModuleInfo, config: LintConfig) -> List[Finding]:
        guards = _guard_map(module)
        if not guards:
            return []
        out: List[Finding] = []
        self._visit(module, module.tree, guards, with_stack=[],
                    fn_stack=[], out=out)
        return out

    def _visit(self, module, node, guards, with_stack, fn_stack, out):
        if isinstance(node, ast.With):
            items = []
            for item in node.items:
                try:
                    items.append(ast.unparse(item.context_expr))
                except Exception:
                    pass
            with_stack = with_stack + items
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_stack = fn_stack + [node.name]
            # a fresh frame: `with` scopes don't leak into nested defs
            with_stack = []
        self._check_node(module, node, guards, with_stack, fn_stack, out)
        for child in ast.iter_child_nodes(node):
            self._visit(module, child, guards, with_stack, fn_stack, out)

    def _check_node(self, module, node, guards, with_stack, fn_stack, out):
        def held(lock: str) -> bool:
            return lock in with_stack

        def flag(attr, lock, lineno, col, how):
            out.append(Finding(
                self.id, module.relpath, lineno, col,
                f"{how} of `{attr}` outside `with {lock}:` (declared "
                f"guard)"))

        in_init = bool(fn_stack) and fn_stack[-1] in ("__init__", "__new__")

        def exempt(attr: str) -> bool:
            # inside __init__ the instance under construction is not
            # yet shared — its OWN attrs mutate freely; module-level
            # guarded names get no such pass
            return in_init and attr.startswith("self.")

        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for t in targets:
            if isinstance(t, ast.Subscript):
                attr = dotted_name(t.value)
                if (attr in guards and not held(guards[attr])
                        and not exempt(attr)):
                    flag(attr, guards[attr], t.lineno, t.col_offset,
                         "item assignment" if not isinstance(
                             node, ast.Delete) else "item deletion")
            else:
                attr = dotted_name(t)
                if (attr in guards and not held(guards[attr])
                        and not in_init and fn_stack):
                    # rebinding outside __init__ swaps the container
                    # under concurrent readers
                    flag(attr, guards[attr], t.lineno, t.col_offset,
                         "rebinding")
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            attr = dotted_name(node.func.value)
            if (attr in guards and not held(guards[attr])
                    and not exempt(attr)):
                flag(attr, guards[attr], node.lineno, node.col_offset,
                     f"`.{node.func.attr}()`")


class TPL007LockOrderCycle:
    """A cycle in the static lock-acquisition graph is a deadlock
    hazard: two threads entering it from different nodes can block each
    other forever. The graph is built interprocedurally by
    :mod:`.locks` from the declared locks (``# tpulint: lock=<name>``
    annotations + the TPL006 guard table), following call edges within
    the linted code. Each cycle is reported ONCE, with the witness path
    of every edge on it — both directions of a 2-cycle name the exact
    acquisition sites to untangle."""

    id = "TPL007"

    def check_project(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        world = _lock_world(project)
        for cyc in world.cycles():
            ring = " → ".join(cyc.nodes + (cyc.nodes[0],))
            wits = "; ".join(f"[{e.src}→{e.dst}] {e.witness}"
                             for e in cyc.edges)
            first = cyc.edges[0]
            out.append(Finding(
                self.id, first.path, first.line, 0,
                f"lock-order cycle {ring} — deadlock hazard; {wits}"))
        return out


class TPL008AtomicityViolation:
    """Check-then-act across a lock release: a value read from a
    guarded container inside ``with <lock>:`` feeds a guarded write in
    a *different* ``with`` block of the SAME lock. Between the two
    blocks another thread may have invalidated the read — merge the
    blocks, or annotate ``# tpulint: atomic-ok`` (read or write line)
    when the gap is intentional (e.g. the value is a snapshot by
    design)."""

    id = "TPL008"

    def check(self, module: ModuleInfo, config: LintConfig) -> List[Finding]:
        guards = _guard_map(module)
        decls = module_lock_decls(
            module, tuple(sorted(set(guards.values()))))
        lock_exprs = {d.expr for d in decls} | set(guards.values())
        if not lock_exprs:
            return []
        ok_lines = {i for i, line in enumerate(module.lines, 1)
                    if _ATOMIC_OK_RE.search(line)}

        def annotated(line: int) -> bool:
            return line in ok_lines or (line - 1) in ok_lines

        out: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.extend(self._check_fn(module, fn, guards, lock_exprs,
                                      annotated))
        return out

    def _check_fn(self, module, fn, guards, lock_exprs, annotated):
        nested: Set[ast.AST] = set()
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.update(ast.walk(sub))
        blocks: List[Tuple[str, ast.With]] = []
        for node in ast.walk(fn):
            if node in nested or not isinstance(node, ast.With):
                continue
            for item in node.items:
                try:
                    expr = ast.unparse(item.context_expr)
                except Exception:
                    continue
                if expr in lock_exprs:
                    blocks.append((expr, node))
        out: List[Finding] = []
        for i, (lock, block_a) in enumerate(blocks):
            attrs = {a for a, lk in guards.items() if lk == lock}
            if not attrs:
                continue
            reads = self._guarded_reads(block_a, attrs)
            if not reads:
                continue
            a_nodes = set(ast.walk(block_a))
            for lock_b, block_b in blocks[i + 1:]:
                if lock_b != lock or block_b in a_nodes:
                    continue
                for wnode, attr in self._guarded_writes(block_b, attrs):
                    used = {n.id for n in ast.walk(wnode)
                            if isinstance(n, ast.Name)} & set(reads)
                    if not used:
                        continue
                    name = sorted(used)[0]
                    rline = reads[name]
                    if annotated(wnode.lineno) or annotated(rline):
                        continue
                    out.append(Finding(
                        self.id, module.relpath, wnode.lineno, 0,
                        f"check-then-act across `{lock}` release: "
                        f"`{name}` (read from a guarded container at "
                        f"line {rline}) feeds this guarded write of "
                        f"`{attr}` in a different `with {lock}:` block "
                        f"— merge the critical sections or annotate "
                        f"`# tpulint: atomic-ok`"))
        return out

    @staticmethod
    def _guarded_reads(block: ast.With, attrs: Set[str]) -> Dict[str, int]:
        """name -> read line for ``n = ...<guarded attr>...`` inside
        the block."""
        reads: Dict[str, int] = {}
        for node in ast.walk(block):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            for sub in ast.walk(node.value):
                if dotted_name(sub) in attrs:
                    reads.setdefault(node.targets[0].id, node.lineno)
                    break
        return reads

    @staticmethod
    def _guarded_writes(block: ast.With, attrs: Set[str]):
        """(statement node, attr) for every guarded-container write in
        the block — same mutation shapes TPL006 patrols."""
        for node in ast.walk(block):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS):
                attr = dotted_name(node.func.value)
                if attr in attrs:
                    yield node, attr
                continue
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                attr = dotted_name(base)
                if attr in attrs:
                    yield node, attr


class TPL009BlockingUnderLock:
    """Blocking or unbounded-time work reached while a declared lock is
    held — file I/O, ``CheckpointManager.restore``, compile builds
    (``StaticFunction._build``), ``time.sleep``, socket ops,
    ``Thread.join``, engine ``step``. Every other thread touching that
    lock stalls behind the slow holder (the repo convention is
    copy-under-lock, act outside). Interprocedural: a call chain that
    reaches the blocking site counts, with the chain in the message."""

    id = "TPL009"

    def check_project(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        world = _lock_world(project)
        for key in sorted(world.fns):
            fn = world.fns[key]
            direct_lines: Set[int] = set()
            for held, desc, line in fn.blocking:
                if not held:
                    continue
                direct_lines.add(line)
                out.append(Finding(
                    self.id, fn.relpath, line, 0,
                    f"blocking call {desc} while holding lock "
                    f"`{held[-1]}` — copy under the lock, do the slow "
                    f"work outside"))
            for held, callname, line in fn.calls:
                if not held or line in direct_lines:
                    continue
                reached = {}
                for g in world.resolve(fn, callname):
                    for desc, site in world.blocks[g.key].items():
                        reached.setdefault(desc, site)
                if not reached:
                    continue
                desc = sorted(reached)[0]
                path, wline, chain = reached[desc]
                via = f" via {chain}" if chain else ""
                out.append(Finding(
                    self.id, fn.relpath, line, 0,
                    f"call `{callname}()`{via} reaches blocking {desc} "
                    f"({path}:{wline}) while holding lock "
                    f"`{held[-1]}` — copy under the lock, do the slow "
                    f"work outside"))
        return out


class TPL010TraceEventParity:
    """Every literal tracer ``.emit("name", ...)`` site uses an event
    name cataloged in docs/OBSERVABILITY.md's event table, and every
    cataloged event has an emit site. The trace is the post-mortem
    record of the request lifecycle — an undocumented event is a dump
    nobody can read, a documented ghost is a timeline gap nobody will
    notice until the 3 a.m. incident (same discipline as TPL003/004)."""

    id = "TPL010"

    def check_project(self, project: Project) -> List[Finding]:
        out: List[Finding] = []
        config = project.config
        emits: List[TraceEmit] = []
        for mod in project.modules:
            emits.extend(collect_trace_emits(mod.tree, mod.relpath))
        doc_path = config.observability_doc
        doc_rel = os.path.relpath(doc_path, config.root).replace(os.sep, "/")
        if not emits and not project.full_scope:
            return out     # targeted lint of trace-free modules
        if not os.path.isfile(doc_path):
            if emits:
                out.append(Finding(self.id, doc_rel, 1, 0,
                                   "observability catalog doc not found"))
            return out
        documented = parse_event_doc(doc_path)
        by_name: Dict[str, List[TraceEmit]] = {}
        for e in emits:
            by_name.setdefault(e.name, []).append(e)
        for name, elist in sorted(by_name.items()):
            first = min(elist, key=lambda e: (e.relpath, e.line))
            if not _in_scope(first.relpath, config.metric_doc_scope):
                continue
            if name not in documented:
                out.append(Finding(
                    self.id, first.relpath, first.line, 0,
                    f"trace event `{name}` is emitted but not cataloged "
                    f"in {doc_rel}"))
        if project.full_scope:
            # docs→code direction: full-scope runs only (see TPL003)
            for name, lineno in sorted(documented.items()):
                if name not in by_name:
                    out.append(Finding(
                        self.id, doc_rel, lineno, 0,
                        f"cataloged trace event `{name}` has no literal "
                        f"emit site in the linted code"))
        return out


FILE_RULES = [TPL001HostSyncInCompiled(), TPL002RecompileHazard(),
              TPL005UnseededRandomness(), TPL006LockDiscipline(),
              TPL008AtomicityViolation()]
PROJECT_RULES = [TPL003MetricCatalogParity(), TPL004FaultPointParity(),
                 TPL007LockOrderCycle(), TPL009BlockingUnderLock(),
                 TPL010TraceEventParity()]
RULE_IDS = ("TPL001", "TPL002", "TPL003", "TPL004", "TPL005", "TPL006",
            "TPL007", "TPL008", "TPL009", "TPL010")


def lock_graph_for(project: Project) -> dict:
    """The static lock-acquisition graph of a linted project (nodes,
    witnessed edges, cycles) — `tools/tpulint.py --lock-graph` and the
    --json payload consume this; it is the same LockWorld TPL007/TPL009
    judged, so what reviewers eyeball IS what the gate enforced."""
    return _lock_world(project).graph()
