"""tpulint core: findings, suppressions, baseline, and the lint driver.

The static half of the repo's invariants (docs/ANALYSIS.md): runtime
tests prove "decode compiles once" / "no host sync in the step" /
"catalogs match the code" one drill at a time; this pass makes each of
them a property the repo cannot silently lose — a rule fires at the
commit that breaks the invariant, not at the incident that reveals it.

Stdlib-only **and paddle_tpu-import-free by design**: the linter must
run (and CI must gate on it) without importing jax or the package under
analysis — ``tools/tpulint.py`` loads this package standalone, so a
broken ``paddle_tpu/__init__`` can't take the linter down with it.

Vocabulary:

- **Finding** — one rule violation at ``path:line:col``. Its identity
  for baseline purposes is ``(rule, path, message)`` — line numbers are
  display-only, so unrelated edits above a baselined finding don't
  churn the baseline file.
- **Suppression** — ``# tpulint: disable=TPL001`` (comma-list or
  ``all``) on the flagged line, or on a comment-only line directly
  above it. Suppressions are counted, never silent.
- **Baseline** — ``tools/tpulint_baseline.json``: findings that predate
  the rule and are accepted with a per-entry note. The CLI exits 0 when
  every finding is baselined; ``--write-baseline`` regenerates the file.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "LintConfig", "LintResult", "ModuleInfo", "Project",
    "iter_py_files", "lint_paths", "load_baseline", "parse_module",
    "split_baseline", "to_json", "to_text", "write_baseline",
]

_DISABLE_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``key`` (rule, path, message) is the stable
    identity the baseline matches on; ``line``/``col`` locate it for
    humans and for same-line suppressions."""

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclass
class LintConfig:
    """Where the repo lives and where the doc catalogs are. Tests point
    the doc paths at fixture files; the CLI uses the repo defaults."""

    root: str
    observability_doc: Optional[str] = None   # default <root>/docs/OBSERVABILITY.md
    resilience_doc: Optional[str] = None      # default <root>/docs/RESILIENCE.md
    # TPL005 only patrols the paths whose correctness depends on seeded
    # determinism (PR 7's contract); fixtures widen this to ("",).
    tpl005_scopes: Tuple[str, ...] = (
        "paddle_tpu/serving", "paddle_tpu/faults", "paddle_tpu/checkpoint",
        "paddle_tpu/loadgen")
    # TPL003's code->docs direction only demands documentation for
    # instruments registered inside the package itself — a demo script
    # registering a scratch series shouldn't gate CI.
    metric_doc_scope: str = "paddle_tpu"

    def __post_init__(self):
        self.root = os.path.abspath(self.root)
        if self.observability_doc is None:
            self.observability_doc = os.path.join(
                self.root, "docs", "OBSERVABILITY.md")
        if self.resilience_doc is None:
            self.resilience_doc = os.path.join(
                self.root, "docs", "RESILIENCE.md")


@dataclass
class ModuleInfo:
    """One parsed source file plus everything rules need: the AST, the
    raw lines, and the per-line suppression map."""

    path: str                  # absolute
    relpath: str               # repo-relative, posix
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)

    def suppressed(self, rule: str, line: int) -> bool:
        """Same-line disable, or a disable on a comment-only line
        directly above the finding."""
        for cand in (line, line - 1):
            rules = self.suppressions.get(cand)
            if rules is None:
                continue
            if cand == line - 1 and not _COMMENT_ONLY_RE.match(
                    self.lines[cand - 1] if cand - 1 < len(self.lines)
                    else ""):
                continue
            if "all" in rules or rule in rules:
                return True
        return False


@dataclass
class Project:
    """Everything the repo-level rules see: all parsed modules plus the
    doc catalogs named by the config. ``full_scope`` is True when the
    lint run covers the whole registration universe (the repo root or
    the paddle_tpu package) — the docs→code parity direction only runs
    then, so a targeted lint of one file isn't drowned in 'documented
    but unregistered' findings whose registration sites simply weren't
    in the linted subset."""

    config: LintConfig
    modules: List[ModuleInfo] = field(default_factory=list)
    full_scope: bool = True

    def module(self, relpath: str) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int = 0
    files: int = 0
    baselined: int = 0
    # the parsed project, for post-lint consumers (the CLI's
    # --lock-graph reuses the modules + the cached LockWorld instead of
    # re-parsing the repo)
    project: Optional[Project] = None


def _collect_suppressions(source: str) -> Dict[int, Set[str]]:
    """Per-physical-line ``# tpulint: disable=...`` map, via tokenize so
    a disable string inside a literal never arms a suppression."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                out.setdefault(tok.start[0], set()).update(rules)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparseable files already yield a TPL000 finding; no
        # suppressions is the safe default
        pass
    return out


def parse_module(path: str, root: str) -> Tuple[Optional[ModuleInfo],
                                                Optional[Finding]]:
    relpath = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, Finding("TPL000", relpath, e.lineno or 1, e.offset or 0,
                             f"syntax error: {e.msg}")
    except OSError as e:
        return None, Finding("TPL000", relpath, 1, 0, f"unreadable: {e}")
    mod = ModuleInfo(path=os.path.abspath(path), relpath=relpath,
                     source=source, tree=tree,
                     lines=source.splitlines(),
                     suppressions=_collect_suppressions(source))
    return mod, None


def iter_py_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand files/dirs into a sorted, de-duplicated .py file list.
    ``__pycache__`` and hidden directories are skipped."""
    seen: Set[str] = set()
    out: List[str] = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(p):
            # a typo'd path must fail loudly — a gate that silently
            # lints nothing is worse than no gate
            raise FileNotFoundError(f"lint path does not exist: {p}")
        if os.path.isfile(p):
            if not p.endswith(".py"):
                # same fail-loudly contract as the missing-path case:
                # a lane pointed at a .pyi/.pyc/doc file must not
                # "pass" by linting nothing
                raise ValueError(f"not a .py file: {p}")
            cand = [p]
        else:
            cand = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                cand.extend(os.path.join(dirpath, f)
                            for f in sorted(filenames) if f.endswith(".py"))
        for c in cand:
            c = os.path.abspath(c)
            if c not in seen:
                seen.add(c)
                out.append(c)
    return out


def lint_paths(paths: Sequence[str], config: LintConfig) -> LintResult:
    """Parse every file under ``paths``, run the per-file rules, then
    the repo-level (catalog-parity) rules, and apply suppressions."""
    from .rules import FILE_RULES, PROJECT_RULES

    roots = {os.path.abspath(config.root),
             os.path.join(os.path.abspath(config.root), "paddle_tpu")}
    expanded = {os.path.abspath(p if os.path.isabs(p)
                                else os.path.join(config.root, p))
                for p in paths}
    project = Project(config=config,
                      full_scope=bool(roots & expanded))
    findings: List[Finding] = []
    files = iter_py_files(paths, config.root)
    for path in files:
        mod, err = parse_module(path, config.root)
        if err is not None:
            findings.append(err)
            continue
        project.modules.append(mod)

    for mod in project.modules:
        for rule in FILE_RULES:
            findings.extend(rule.check(mod, config))
    for rule in PROJECT_RULES:
        findings.extend(rule.check_project(project))

    kept: List[Finding] = []
    suppressed = 0
    by_path = {m.relpath: m for m in project.modules}
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.rule, f.line):
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))
    return LintResult(findings=kept, suppressed=suppressed,
                      files=len(files), project=project)


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a tpulint baseline "
                         "(expected {{'version': 1, 'entries': [...]}})")
    entries = list(data["entries"])
    for i, e in enumerate(entries):
        # validate here so a hand-edit/bad merge is a clean exit-2
        # "bad baseline", not an AttributeError deep in split_baseline
        # masquerading as exit-1 findings
        if not isinstance(e, dict):
            raise ValueError(f"{path}: entries[{i}] is not an object")
    return entries


def split_baseline(findings: Sequence[Finding],
                   entries: Sequence[dict]) -> Tuple[List[Finding],
                                                     List[Finding]]:
    """(new, baselined): a finding is baselined when an entry matches
    its (rule, path, message) key. One entry absorbs any number of
    identical findings (e.g. the same message at two call sites)."""
    keys = {(e.get("rule"), e.get("path"), e.get("message"))
            for e in entries}
    new = [f for f in findings if f.key not in keys]
    old = [f for f in findings if f.key in keys]
    return new, old


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Regenerate the baseline. Notes of entries whose (rule, path,
    message) key survives are PRESERVED — regeneration must never
    destroy curated justifications; only new entries get the TODO."""
    kept_notes: Dict[Tuple[str, str, str], str] = {}
    if os.path.isfile(path):
        try:
            for e in load_baseline(path):
                key = (e.get("rule"), e.get("path"), e.get("message"))
                if e.get("note"):
                    kept_notes[key] = e["note"]
        except (OSError, ValueError, json.JSONDecodeError):
            pass    # unreadable old baseline: regenerate from scratch
    entries = []
    seen: Set[Tuple[str, str, str]] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({"rule": f.rule, "path": f.path, "line": f.line,
                        "message": f.message,
                        "note": kept_notes.get(f.key,
                                               "TODO: justify or fix")})
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2,
                  sort_keys=False)
        fh.write("\n")


# ------------------------------------------------------------------ output
def to_text(result: LintResult, new: Sequence[Finding]) -> str:
    lines = [f.render() for f in new]
    lines.append(f"tpulint: {len(new)} finding(s) "
                 f"({result.baselined} baselined, "
                 f"{result.suppressed} suppressed, "
                 f"{result.files} files)")
    return "\n".join(lines)


def to_json(result: LintResult, new: Sequence[Finding],
            lock_graph: Optional[dict] = None) -> str:
    """Stable (sorted, timestamp-free) JSON for diffing in CI logs.
    ``lock_graph`` (the TPL007 acquisition graph) rides along when the
    caller passes it — the CLI always does."""
    payload = {
        "version": 1,
        "files": result.files,
        "suppressed": result.suppressed,
        "baselined": result.baselined,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "col": f.col, "message": f.message}
            for f in new],
    }
    if lock_graph is not None:
        payload["lock_graph"] = lock_graph
    return json.dumps(payload, indent=2, sort_keys=True)
