"""Traced-context tracking: which functions compile, which values trace.

Two questions every compiled-code rule needs answered:

1. **Which function bodies run under a tracer?** Detected per module:
   ``@jit`` / ``@to_static`` / ``@jax.jit``-style decorators, local
   functions passed by name into ``jit.StaticFunction(...)`` /
   ``jax.jit(...)`` / ``to_static(...)`` / ``BucketedFunction(...)``
   (the engine's ``step_fn`` idiom — renamed to ``serving_step`` via
   ``__name__`` for the compile counter, which is also recognized), and
   every function
   lexically nested inside one (helpers like the decode step's
   ``batched_sample``/``one_row`` trace with their parent).

2. **Which values inside such a body are traced?** A lightweight taint
   pass: the function's parameters seed the traced set; assignments,
   loop targets, and comprehensions propagate it. Static escapes —
   ``.shape`` / ``.dtype`` / ``.ndim`` / ``.size`` / ``len()`` — yield
   Python values at trace time and drop the taint, so ``h.shape[-1]``
   in an index position never fires a rule. Results of ``jnp.*`` /
   ``jax.*`` calls are traced regardless of their arguments (a
   ``jnp.zeros(())`` is a tracer even with constant args).

The tracker is deliberately *per-module* and *syntactic*: no imports
are resolved, no cross-file calls followed. That keeps false positives
low (a trunk's ``forward`` is only linted when something in the same
file compiles it) at the cost of not chasing invariants through call
chains — the runtime drills still own that half.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["CompiledScopes", "Taint", "dotted_name"]

# a call whose callee ends in one of these wraps/compiles its function
# argument (jit.StaticFunction, jax.jit, paddle.jit.to_static, pjit, ...)
_WRAPPER_TAILS = {"StaticFunction", "jit", "to_static", "pjit",
                  "BucketedFunction"}
# decorator names that mark the decorated def itself as compiled
_DECORATOR_TAILS = _WRAPPER_TAILS
# fn.__name__ = "<one of these>" marks fn as a compiled step fn even if
# the wrap happens in code the walker can't see
_KNOWN_COMPILED_NAMES = {"serving_step", "serving_prefill",
                         "serving_decode"}

_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}
# methods whose RESULT is a host value, not a tracer — calling them on
# a traced receiver is TPL001's finding; their result must not keep
# propagating taint (float(x.item()) is one sync, one finding)
_HOST_RESULT_METHODS = {"item", "tolist", "numpy"}
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                 "id", "print"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class CompiledScopes:
    """Per-module index of compiled function defs (and why)."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        # name -> every def with that name, any nesting level
        self._defs: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs.setdefault(node.name, []).append(node)
        self.compiled: Dict[ast.AST, str] = {}
        self._mark_decorated()
        self._mark_wrapped()
        self._mark_renamed()
        self._mark_nested()
        # names/attrs bound to compiled-callable objects in this module
        # (for the TPL002 call-site check): "prog", "self._decode_prog"
        self.compiled_bindings: Dict[str, Tuple[int, str]] = {}
        self._collect_bindings()

    # ---------------------------------------------------------- detection
    def _mark(self, fn: ast.AST, reason: str) -> None:
        self.compiled.setdefault(fn, reason)

    def _mark_decorated(self) -> None:
        for defs in self._defs.values():
            for fn in defs:
                for dec in fn.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    tail = _tail(target)
                    if tail in _DECORATOR_TAILS:
                        self._mark(fn, f"decorated @{tail}")
                    # @functools.partial(jax.jit, ...)
                    if (isinstance(dec, ast.Call)
                            and _tail(dec.func) == "partial" and dec.args
                            and _tail(dec.args[0]) in _WRAPPER_TAILS):
                        self._mark(fn, "decorated @partial(jit)")

    def _mark_wrapped(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _tail(node.func) not in _WRAPPER_TAILS:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in self._defs:
                    for fn in self._defs[arg.id]:
                        self._mark(fn, f"passed to {_tail(node.func)}()")

    def _mark_renamed(self) -> None:
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and node.targets[0].attr == "__name__"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value in _KNOWN_COMPILED_NAMES
                    and isinstance(node.targets[0].value, ast.Name)):
                for fn in self._defs.get(node.targets[0].value.id, []):
                    self._mark(fn, f"renamed to {node.value.value!r}")

    def _mark_nested(self) -> None:
        for fn in list(self.compiled):
            for sub in ast.walk(fn):
                if (sub is not fn and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef))):
                    self._mark(sub, f"nested in compiled {fn.name!r}")

    def _collect_bindings(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and _tail(value.func) in _WRAPPER_TAILS):
                continue
            for t in node.targets:
                name = dotted_name(t)
                if name:
                    self.compiled_bindings[name] = (
                        node.lineno, _tail(value.func) or "jit")


class Taint:
    """Traced-value taint inside ONE compiled function body.

    Single forward pass in source order. Taint is **position-gated**:
    ``traced`` maps each name to the first line from which it carries a
    tracer, and a ``Name`` use only counts as traced at or after that
    line — so ``n = 4; for i in range(n): ...; n = x * 2`` does not
    retroactively flag the loop. Loops don't iterate to a fixpoint
    (taint flowing textually backward inside a loop body is a miss) —
    consistent with the errs-toward-silence policy; the runtime drills
    own that residue. Comprehension variables are scoped to the
    comprehension, as in Python 3."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        # name -> first line (inclusive) from which it is traced
        self.traced: Dict[str, int] = {}
        # name -> [(start, end)) intervals closed by a later rebind to
        # an untraced value — `n = x * 2; n = 0` stops carrying taint
        # at the second assignment
        self.closed: Dict[str, List[Tuple[int, int]]] = {}
        self._taint_params(fn)
        for stmt in fn.body:
            self._visit_stmt(stmt)

    def _taint_params(self, fn: ast.AST) -> None:
        args = fn.args
        names = [a.arg for a in (list(args.posonlyargs) + list(args.args)
                                 + list(args.kwonlyargs))]
        if args.vararg is not None:
            names.append(args.vararg.arg)
        if args.kwarg is not None:
            names.append(args.kwarg.arg)
        for n in names:
            self._taint_name(n, fn.lineno)

    def _taint_name(self, name: str, line: int) -> None:
        prev = self.traced.get(name)
        if prev is None or line < prev:
            self.traced[name] = line

    def _untaint_name(self, name: str, line: int) -> None:
        start = self.traced.pop(name, None)
        if start is not None and start < line:
            self.closed.setdefault(name, []).append((start, line))

    # ------------------------------------------------------------ traversal
    def _visit_stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs trace with the parent; their params join the
            # traced set under their own names
            self._taint_params(node)
            for stmt in node.body:
                self._visit_stmt(stmt)
            return
        self._scan_named_exprs(node)
        if isinstance(node, ast.Assign):
            if self.is_traced(node.value):
                for t in node.targets:
                    self._taint_target(t, node.lineno)
            else:
                for t in node.targets:
                    self._untaint_target(t, node.lineno)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                if self.is_traced(node.value):
                    self._taint_target(node.target, node.lineno)
                else:
                    self._untaint_target(node.target, node.lineno)
        elif isinstance(node, ast.AugAssign):
            if self.is_traced(node.value) or self.is_traced(node.target):
                self._taint_target(node.target, node.lineno)
        elif isinstance(node, ast.For):
            if self.is_traced(node.iter):
                self._taint_target(node.target, node.lineno)
            else:
                self._untaint_target(node.target, node.lineno)
        elif isinstance(node, ast.With):
            for item in node.items:
                if (item.optional_vars is not None
                        and self.is_traced(item.context_expr)):
                    self._taint_target(item.optional_vars, node.lineno)
        for child in ast.iter_child_nodes(node):
            # excepthandler / match_case are not stmt subclasses but
            # carry statement bodies — skipping them would blind the
            # taint pass to everything inside except/case blocks
            if isinstance(child, (ast.stmt, ast.excepthandler,
                                  ast.match_case)):
                self._visit_stmt(child)

    def _scan_named_exprs(self, node: ast.AST) -> None:
        """Walrus targets bind in the enclosing scope: taint (or
        untaint) them from THIS statement's expressions, without
        descending into nested statements — those bind at their own
        visit."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.excepthandler,
                                  ast.match_case)):
                continue
            if isinstance(child, ast.NamedExpr):
                if self.is_traced(child.value):
                    self._taint_target(child.target, child.lineno)
                else:
                    self._untaint_target(child.target, child.lineno)
            self._scan_named_exprs(child)

    def _taint_target(self, target: ast.AST, line: int) -> None:
        for n in self._target_names(target):
            self._taint_name(n, line)

    def _untaint_target(self, target: ast.AST, line: int) -> None:
        for n in self._target_names(target):
            self._untaint_name(n, line)

    @staticmethod
    def _target_names(target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for elt in target.elts:
                out.extend(Taint._target_names(elt))
            return out
        if isinstance(target, ast.Starred):
            return Taint._target_names(target.value)
        # subscript/attribute stores mutate an existing (already
        # traced-or-not) object; nothing new to taint
        return []

    def _comp_is_traced(self, node: ast.AST, parts: List[ast.AST]) -> bool:
        """Comprehension query with the loop variables tainted only for
        the duration of the evaluation — they are scoped in Python 3
        and must not leak into the enclosing body."""
        saved: Dict[str, Optional[int]] = {}
        for gen in node.generators:
            if self.is_traced(gen.iter):
                for n in self._target_names(gen.target):
                    if n not in saved:
                        saved[n] = self.traced.get(n)
                    self.traced[n] = 0      # active at any line inside
        try:
            return any(self.is_traced(p) for p in parts)
        finally:
            for n, prev in saved.items():
                if prev is None:
                    self.traced.pop(n, None)
                else:
                    self.traced[n] = prev

    # ------------------------------------------------------------ queries
    def is_traced(self, node: ast.AST) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            line = getattr(node, "lineno", None)
            since = self.traced.get(node.id)
            if since is not None and (line is None or line >= since):
                return True
            if line is not None:
                return any(start <= line < end for start, end
                           in self.closed.get(node.id, ()))
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False        # static at trace time
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            tail = _tail(node.func)
            if tail in _STATIC_CALLS:
                return False
            root = dotted_name(node.func) or ""
            if root.split(".", 1)[0] in ("jnp", "jax"):
                return True         # jnp/jax results are tracers
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr not in _HOST_RESULT_METHODS
                    and self.is_traced(node.func.value)):
                # method call on a traced receiver — x.sum(),
                # x.astype(...): the paddle-style method API returns
                # tracers just like the jnp.* spelling
                return True
            return (any(self.is_traced(a) for a in node.args)
                    or any(self.is_traced(kw.value)
                           for kw in node.keywords))
        if isinstance(node, ast.Subscript):
            return self.is_traced(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not y` are identity checks on the
            # PYTHON object — static under trace, never a sync
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self.is_traced(node.left)
                    or any(self.is_traced(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return (self.is_traced(node.body) or self.is_traced(node.test)
                    or self.is_traced(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_traced(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return (any(k is not None and self.is_traced(k)
                        for k in node.keys)
                    or any(self.is_traced(v) for v in node.values))
        if isinstance(node, ast.Starred):
            return self.is_traced(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.is_traced(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._comp_is_traced(node, [node.elt])
        if isinstance(node, ast.DictComp):
            return self._comp_is_traced(node, [node.key, node.value])
        if isinstance(node, ast.JoinedStr):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.FormattedValue):
            return self.is_traced(node.value)
        if isinstance(node, ast.Lambda):
            return False
        return False
