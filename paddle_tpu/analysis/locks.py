"""Concurrency analysis: the static lock-acquisition graph.

The shared machinery behind TPL007/TPL008/TPL009 (docs/ANALYSIS.md):

- **Declared locks.** A lock enters the analysis either through a
  ``# tpulint: lock=<name>`` annotation on its creation line (the
  canonical way — the name becomes the graph node, e.g. ``router`` or
  ``metrics.registry``) or as the guard expression of a TPL006 row /
  ``# tpulint: guard=`` annotation (fallback-named ``<stem>:<expr>``).
  Only *declared* locks are tracked: an undeclared ``threading.Lock``
  is invisible, so the rules err toward silence, never toward noise.

- **Acquisition graph.** For every function we track which declared
  locks are held (lexical ``with <lock>:`` nesting, TPL006-style) at
  each further acquisition and at each call site. Call edges within
  the linted code are followed interprocedurally — ``self.m()`` and
  bare ``f()`` resolve within the module, ``x.m()`` resolves by method
  name across the project (generic container/str tails excluded) — so
  "holding `router`, a call chain reaches ``with metrics.family:``"
  becomes the edge ``router → metrics.family`` with a witness site.

- **Cycles** (TPL007) are deadlock hazards: two threads entering the
  cycle from different nodes can block each other forever. Each cycle
  is reported once, with the witness path of EVERY edge on it.

- **Blocking reach** (TPL009): calls that can block or take unbounded
  time (file I/O, checkpoint restore, compile builds, ``time.sleep``,
  socket ops, ``Thread.join``, engine ``step``) reached — directly or
  through calls — while a declared lock is held.

Everything here is syntactic (AST + the lexical with-stack, no import
resolution, no type inference), same contract as the rest of tpulint.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo, Project
from .scopes import dotted_name

__all__ = [
    "LockDecl", "LockWorld", "lock_graph", "lock_graph_dot",
    "module_lock_decls",
]

_LOCK_DECL_RE = re.compile(r"#\s*tpulint:\s*lock=(\S+)")

# Default graph-node names for locks the _LOCK_TABLE already knows but
# whose source predates the ``# tpulint: lock=`` form. In-source
# annotations take precedence; these keep the graph readable if an
# annotation is ever dropped.
_DEFAULT_LOCK_NAMES: Dict[Tuple[str, str], str] = {
    ("paddle_tpu/serving/router.py", "self._lock"): "router",
    ("paddle_tpu/faults/injection.py", "_lock"): "faults.catalog",
    ("paddle_tpu/checkpoint/manager.py", "_LIVE_TMP_LOCK"): "ckpt.live_tmp",
}

# Method/attr tails NEVER followed in cross-module call resolution:
# container and str methods shadow too many project functions (a
# `d.get()` must not resolve to `MetricsRegistry.get`). Same-module
# `self.m()` / bare `f()` calls are resolved precisely and don't pass
# through this gate.
_GENERIC_TAILS = frozenset({
    "get", "pop", "popitem", "items", "keys", "values", "copy", "update",
    "add", "append", "remove", "discard", "clear", "setdefault", "extend",
    "insert", "sort", "index", "count", "join", "split", "rsplit",
    "strip", "lstrip", "rstrip", "startswith", "endswith", "format",
    "encode", "decode", "read", "write", "close", "open", "flush",
    "acquire", "release", "locked", "put", "send", "recv", "next",
    "wait", "notify", "notify_all", "start", "run", "is_alive", "reset",
    "search", "match", "sub", "findall", "group", "lower", "upper",
    "replace", "rename", "exists", "isfile", "isdir", "splitlines",
})

# -- blocking-call classification (TPL009) ------------------------------
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "os.fsync", "os.replace", "os.rename", "select.select",
    "socket.create_connection", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen", "shutil.rmtree", "shutil.copytree",
    "shutil.copyfile", "shutil.move",
})
_BLOCKING_TAILS = frozenset({
    "restore", "_build", "sleep", "urlopen", "recv", "recv_into",
    "sendall", "accept", "connect", "step",
})


def blocking_desc(call: ast.Call) -> Optional[str]:
    """Human-readable description when ``call`` can block or take
    unbounded time, else None. ``.join()`` is special-cased: thread
    joins block, ``os.path.join`` / ``"sep".join`` don't (a Constant
    receiver has no dotted name and never fires)."""
    func = call.func
    if isinstance(func, ast.Name) and func.id == "open":
        return "file I/O `open()`"
    name = dotted_name(func)
    if not name:
        return None
    if name in _BLOCKING_DOTTED:
        return f"`{name}()`"
    parts = name.split(".")
    tail = parts[-1]
    if tail in _BLOCKING_TAILS:
        return f"`{name}()`"
    if tail == "join" and len(parts) >= 2:
        recv = name.rsplit(".", 1)[0]
        if not recv.endswith("path"):
            return f"thread join `{name}()`"
    return None


@dataclass(frozen=True)
class LockDecl:
    """One declared lock: the expression it is written as at use sites
    (``self._lock`` / ``_pending_lock``), the graph-node name, and the
    class that owns it (None for module-level locks)."""

    expr: str
    name: str
    cls: Optional[str]
    relpath: str
    line: int


def module_lock_decls(module: ModuleInfo,
                      guard_locks: Sequence[str] = ()) -> List[LockDecl]:
    """Declared locks of one module: ``# tpulint: lock=<name>``
    annotations first (class-aware), then default-table rows, then a
    fallback-named decl for every guard-lock expression TPL006 knows
    that no annotation already covers."""
    annotated_lines: Dict[int, str] = {}
    for i, line in enumerate(module.lines, 1):
        m = _LOCK_DECL_RE.search(line)
        if m:
            annotated_lines[i] = m.group(1)
    decls: List[LockDecl] = []
    seen_exprs: Set[str] = set()

    def visit(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, cls)
                continue
            if isinstance(child, ast.Assign):
                targets = child.targets
            elif isinstance(child, ast.AnnAssign):
                targets = [child.target]
            else:
                visit(child, cls)
                continue
            lock_name = annotated_lines.get(child.lineno)
            if lock_name is not None:
                for t in targets:
                    expr = dotted_name(t)
                    if expr:
                        decls.append(LockDecl(expr, lock_name, cls,
                                              module.relpath, child.lineno))
                        seen_exprs.add(expr)
            visit(child, cls)

    visit(module.tree, None)
    stem = module.relpath.rsplit("/", 1)[-1][:-3]
    for expr in guard_locks:
        if expr in seen_exprs:
            continue
        name = _DEFAULT_LOCK_NAMES.get((module.relpath, expr),
                                       f"{stem}:{expr}")
        decls.append(LockDecl(expr, name, None, module.relpath, 0))
        seen_exprs.add(expr)
    return decls


@dataclass
class _FnInfo:
    """One function's lock-relevant summary, gathered in a single walk:
    every acquisition and every call, each with the lock names held at
    that point (lexical ``with`` nesting; nested defs get a fresh
    frame, exactly like TPL006)."""

    key: str
    name: str
    cls: Optional[str]
    relpath: str
    # (held lock names, acquired lock name, line)
    acquisitions: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held lock names, dotted call name, line)
    calls: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)
    # (held lock names, blocking description, line)
    blocking: List[Tuple[Tuple[str, ...], str, int]] = field(
        default_factory=list)


@dataclass(frozen=True)
class LockEdge:
    """``src`` can be held when ``dst`` is acquired; ``witness`` is the
    human-readable evidence path, anchored at ``path:line``."""

    src: str
    dst: str
    path: str
    line: int
    witness: str


@dataclass(frozen=True)
class LockCycle:
    nodes: Tuple[str, ...]
    edges: Tuple[LockEdge, ...]


class LockWorld:
    """The project-wide lock universe: declarations, per-function
    summaries, the interprocedural acquisition/blocking closures, and
    the resulting edge set. Built once per lint run and shared by
    TPL007 and TPL009 (cached on the Project object by rules.py)."""

    def __init__(self, project: Project,
                 guard_locks_of=None):
        self.project = project
        self.decls_by_module: Dict[str, List[LockDecl]] = {}
        self.fns: Dict[str, _FnInfo] = {}
        self._by_tail: Dict[str, List[_FnInfo]] = {}
        self._plain_by_module: Dict[str, Dict[str, List[_FnInfo]]] = {}
        for mod in project.modules:
            guard_locks = (guard_locks_of(mod) if guard_locks_of else ())
            self.decls_by_module[mod.relpath] = module_lock_decls(
                mod, guard_locks)
        # module-level lock attrs unique project-wide: lets a
        # cross-module reference (`dist_ckpt._pending_lock`) match the
        # declaring module's node
        by_attr: Dict[str, Set[str]] = {}
        for decls in self.decls_by_module.values():
            for d in decls:
                if d.cls is None and "." not in d.expr:
                    by_attr.setdefault(d.expr, set()).add(d.name)
        self._unique_module_attrs = {attr: next(iter(names))
                                     for attr, names in by_attr.items()
                                     if len(names) == 1}
        for mod in project.modules:
            self._walk_module(mod)
        self.acquires = self._closure(lambda fn: fn.acquisitions)
        self.blocks = self._closure(lambda fn: fn.blocking)
        self.edges = self._build_edges()

    # ---------------------------------------------------------------- walk
    def _match_lock(self, relpath: str, cls: Optional[str],
                    expr: str) -> Optional[str]:
        cands = [d for d in self.decls_by_module.get(relpath, ())
                 if d.expr == expr]
        exact = [d for d in cands if d.cls == cls and d.cls is not None]
        if exact:
            return exact[0].name
        mod_level = [d for d in cands if d.cls is None]
        if mod_level:
            return mod_level[0].name
        if len(cands) == 1:
            return cands[0].name
        if cands:
            return None          # ambiguous between classes: stay silent
        parts = expr.split(".")
        if len(parts) >= 2 and parts[0] != "self":
            return self._unique_module_attrs.get(parts[-1])
        return None

    def _walk_module(self, mod: ModuleInfo) -> None:
        def visit(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self._walk_fn(mod, child, cls)
                else:
                    visit(child, cls)

        visit(mod.tree, None)

    def _walk_fn(self, mod: ModuleInfo, fn_node, cls: Optional[str]) -> None:
        qual = f"{cls}.{fn_node.name}" if cls else fn_node.name
        key = f"{mod.relpath}::{qual}@{fn_node.lineno}"
        info = _FnInfo(key=key, name=fn_node.name, cls=cls,
                       relpath=mod.relpath)
        self.fns[key] = info
        self._by_tail.setdefault(fn_node.name, []).append(info)
        if cls is None:
            self._plain_by_module.setdefault(
                mod.relpath, {}).setdefault(fn_node.name, []).append(info)

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # fresh frame: lexical `with` scopes don't leak into
                    # nested defs, which run on their own schedule
                    self._walk_fn(mod, child, cls)
                    continue
                if isinstance(child, ast.ClassDef):
                    continue
                child_held = held
                if isinstance(child, ast.With):
                    for item in child.items:
                        try:
                            expr = ast.unparse(item.context_expr)
                        except Exception:
                            continue
                        name = self._match_lock(mod.relpath, cls, expr)
                        if name is None:
                            continue
                        if name not in child_held:
                            info.acquisitions.append(
                                (child_held, name, child.lineno))
                            child_held = child_held + (name,)
                if isinstance(child, ast.Call):
                    callee = dotted_name(child.func)
                    if callee:
                        info.calls.append((child_held, callee,
                                           child.lineno))
                    desc = blocking_desc(child)
                    if desc is not None:
                        info.blocking.append((child_held, desc,
                                              child.lineno))
                walk(child, child_held)

        walk(fn_node, ())

    # ----------------------------------------------------------- resolution
    def resolve(self, fn: _FnInfo, callname: str) -> List[_FnInfo]:
        parts = callname.split(".")
        if len(parts) == 1:
            return list(self._plain_by_module.get(
                fn.relpath, {}).get(parts[0], ()))
        if parts[0] == "self" and len(parts) == 2:
            cands = [g for g in self._by_tail.get(parts[1], ())
                     if g.relpath == fn.relpath and g.cls is not None]
            same_cls = [g for g in cands if g.cls == fn.cls]
            return same_cls or cands
        tail = parts[-1]
        if tail in _GENERIC_TAILS:
            return []
        return list(self._by_tail.get(tail, ()))

    # ------------------------------------------------------------- closures
    def _closure(self, events_of):
        """Transitive summary per function: for acquisitions, the set of
        lock names a call into the function can take (with one witness
        site + chain each); for blocking events, the set of blocking
        descriptions reachable. Fixpoint over the syntactic call graph
        — cycles converge because the maps only grow."""
        out: Dict[str, Dict[str, Tuple[str, int, str]]] = {}
        for key, fn in self.fns.items():
            direct: Dict[str, Tuple[str, int, str]] = {}
            for _held, what, line in events_of(fn):
                direct.setdefault(what, (fn.relpath, line, ""))
            out[key] = direct
        changed = True
        while changed:
            changed = False
            for key, fn in sorted(self.fns.items()):
                mine = out[key]
                for _held, callname, line in fn.calls:
                    for g in self.resolve(fn, callname):
                        for what, (path, wline, chain) in out[g.key].items():
                            if what not in mine:
                                hop = f"`{callname}()`"
                                mine[what] = (path, wline,
                                              hop + (" → " + chain
                                                     if chain else ""))
                                changed = True
        return out

    # ---------------------------------------------------------------- edges
    def _build_edges(self) -> Dict[Tuple[str, str], LockEdge]:
        edges: Dict[Tuple[str, str], LockEdge] = {}

        def add(src: str, dst: str, path: str, line: int, text: str):
            if src == dst:
                return       # re-entrancy is the sanitizer's job
            edges.setdefault((src, dst),
                             LockEdge(src, dst, path, line, text))

        for key in sorted(self.fns):
            fn = self.fns[key]
            for held, lock, line in fn.acquisitions:
                for h in held:
                    add(h, lock, fn.relpath, line,
                        f"holding `{h}`, `with {lock}:` entered at "
                        f"{fn.relpath}:{line}")
            for held, callname, line in fn.calls:
                if not held:
                    continue
                for g in self.resolve(fn, callname):
                    for lock, (path, wline, chain) in sorted(
                            self.acquires[g.key].items()):
                        for h in held:
                            via = (f" via {chain}" if chain else "")
                            add(h, lock, fn.relpath, line,
                                f"holding `{h}`, call `{callname}()` at "
                                f"{fn.relpath}:{line}{via} reaches "
                                f"`with {lock}:` at {path}:{wline}")
        return edges

    # --------------------------------------------------------------- cycles
    def cycles(self) -> List[LockCycle]:
        """One representative simple cycle per strongly-connected
        component of the edge graph (deterministic: nodes visited in
        sorted order). An acyclic graph returns []."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for v in adj.values():
            v.sort()
        sccs = _tarjan(adj)
        out: List[LockCycle] = []
        for comp in sccs:
            comp_set = set(comp)
            if len(comp) == 1:
                continue        # self-edges are filtered at build time
            start = min(comp)
            path = self._find_cycle(start, comp_set, adj)
            if not path:
                continue
            cycle_edges = tuple(
                self.edges[(path[i], path[(i + 1) % len(path)])]
                for i in range(len(path)))
            out.append(LockCycle(tuple(path), cycle_edges))
        out.sort(key=lambda c: c.nodes)
        return out

    @staticmethod
    def _find_cycle(start: str, comp: Set[str],
                    adj: Dict[str, List[str]]) -> List[str]:
        """DFS within one SCC from ``start`` back to ``start``."""
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        best: List[str] = []
        seen: Set[Tuple[str, ...]] = set()
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) > 1:
                    if not best or len(path) < len(best):
                        best = path
                    continue
                if nxt in comp and nxt not in path:
                    key = tuple(path) + (nxt,)
                    if key not in seen:
                        seen.add(key)
                        stack.append((nxt, path + [nxt]))
        return best

    # ------------------------------------------------------------ exports
    def graph(self) -> dict:
        """JSON-ready acquisition graph (stable ordering)."""
        nodes = sorted({d.name for decls in self.decls_by_module.values()
                        for d in decls})
        return {
            "nodes": nodes,
            "edges": [
                {"from": e.src, "to": e.dst, "path": e.path,
                 "line": e.line, "witness": e.witness}
                for (_a, _b), e in sorted(self.edges.items())],
            "cycles": [list(c.nodes) for c in self.cycles()],
        }


def _tarjan(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (recursion-free: the lock graph is tiny, but
    the linter must never die on a pathological fixture)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            children = adj.get(node, ())
            for i in range(pi, len(children)):
                ch = children[i]
                if ch not in index:
                    work[-1] = (node, i + 1)
                    work.append((ch, 0))
                    advanced = True
                    break
                if ch in on_stack:
                    low[node] = min(low[node], index[ch])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(sorted(comp))
    return sccs


def lock_graph(world: LockWorld) -> dict:
    return world.graph()


def lock_graph_dot(graph: dict) -> str:
    """The acquisition graph as Graphviz DOT — `tpulint --lock-graph`;
    cycle edges are drawn red+bold so a hazard is visible at a glance."""
    cyc_edges: Set[Tuple[str, str]] = set()
    for cyc in graph.get("cycles", ()):
        for i, a in enumerate(cyc):
            cyc_edges.add((a, cyc[(i + 1) % len(cyc)]))
    lines = ["digraph lock_order {", "  rankdir=LR;",
             '  node [shape=box, fontname="monospace"];']
    for n in graph["nodes"]:
        lines.append(f'  "{n}";')
    for e in graph["edges"]:
        attrs = f'label="{e["path"]}:{e["line"]}"'
        if (e["from"], e["to"]) in cyc_edges:
            attrs += ', color=red, penwidth=2.0'
        lines.append(f'  "{e["from"]}" -> "{e["to"]}" [{attrs}];')
    lines.append("}")
    return "\n".join(lines) + "\n"
