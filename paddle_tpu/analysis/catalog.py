"""Catalog extraction: metric/fault-point names from code and from docs.

One parser, two consumers: TPL003/TPL004 (static parity, both
directions) and ``tools/metrics_dump.py --check-docs`` (runtime parity:
the live registry from a ``--demo`` run diffed against the same doc
table). Keeping the doc grammar in one place is the point — the moment
the parser and the prose drift, BOTH checks fail on the same line.

Doc grammar (docs/OBSERVABILITY.md, docs/RESILIENCE.md):

- Only **table rows** count (lines starting with ``|``) and only
  outside fenced code blocks — prose and quick-start examples can
  mention any name without registering it in the catalog.
- A metric is a backtick span that IS a metric token:
  ``` `paddle_tpu_foo_total{label,label}` ``` (the ``{...}`` label hint
  is stripped; spans with placeholders like ``<name>`` are skipped —
  they document dynamically-named families).
- A fault point is a backtick span in the row's FIRST cell matching
  ``subsystem.point`` (lowercase dotted), the RESILIENCE.md fault-point
  table shape.

Code grammar:

- A metric registration is ``<registry>.counter|gauge|histogram(name,
  ...)`` where ``<registry>`` looks like a registry (``reg`` / ``_REG``
  / ``registry`` / ``metrics.get_registry()`` / ``get_registry()``).
  ``profiler.record_counter("a.b", v)`` also registers: its bridged
  gauge lands at ``sanitize_metric_name("a.b")``.
- A fault site is a literal first argument to ``faults.point`` /
  ``faults.declare_point`` / ``faults.inject`` (or those names imported
  bare). Non-literal names (``faults.point(point_name)``) are skipped —
  the literal appears at the caller that chose the name.
- A trace emit site (TPL010) is ``<tracer>.emit("name", ...)`` with a
  literal name where ``<tracer>`` looks like a tracer (``trace`` /
  ``_trace`` / ``tracer`` / ``_tracer`` tail, or a ``get_tracer()``
  call) — the receiver shape is the discriminator that keeps
  unrelated ``.emit(...)`` APIs (the ONNX node builder) out of the
  catalog. Doc side: a backtick span in the FIRST cell of an
  OBSERVABILITY.md table row matching ``req.name`` / ``step.name``.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .scopes import dotted_name

__all__ = [
    "FaultSite", "MetricRegistration", "TraceEmit",
    "collect_fault_sites", "collect_label_uses",
    "collect_metric_registrations", "collect_trace_emits",
    "parse_event_doc", "parse_fault_doc", "parse_metric_doc",
    "sanitize_metric_name",
]

_METRIC_TOKEN_RE = re.compile(
    r"^(paddle_tpu_[a-zA-Z0-9_]+)(\{([a-zA-Z0-9_,\s]*)\})?$")
_FAULT_TOKEN_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")
# trace events are namespaced req./step./brownout. — disjoint from
# fault tokens only by convention, so the event catalog lives in
# OBSERVABILITY.md (TPL010) while fault points live in RESILIENCE.md
# (TPL004)
_EVENT_TOKEN_RE = re.compile(r"^(req|step|brownout)\.[a-z][a-z0-9_]*$")
_TRACER_RECEIVER_RE = re.compile(r"^_?tracer?$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")
_REGISTRY_RECEIVER_RE = re.compile(r"^_?reg(istry)?$", re.IGNORECASE)

# the registry's naming funnel, duplicated in miniature so the linter
# never imports paddle_tpu (see paddle_tpu/metrics/registry.py
# sanitize_metric_name — the two are pinned equal by tests)
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def sanitize_metric_name(raw: str) -> str:
    s = re.sub(r"[^a-zA-Z0-9_:]", "_", str(raw))
    if not s or not _NAME_RE.match(s):
        s = "_" + s
    if not s.startswith("paddle_tpu_"):
        s = "paddle_tpu_" + s
    return s


# ------------------------------------------------------------------ doc side
def _table_rows(text: str):
    """(lineno, line) for markdown table rows outside fenced code."""
    fenced = False
    for i, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("```"):
            fenced = not fenced
            continue
        if fenced or not stripped.startswith("|"):
            continue
        if set(stripped) <= {"|", "-", " ", ":"}:
            continue                     # separator row
        yield i, stripped


def parse_metric_doc(path: str) -> Dict[str, Tuple[int, Tuple[str, ...]]]:
    """{metric_name: (lineno, declared label hint)} from the FIRST cell
    of catalog table rows — a prose cross-reference in another row's
    meaning cell must not satisfy parity after the real row is deleted.
    ``{eng}`` is the docs' shorthand for the per-engine
    ``{engine_id, model_id}`` pair and expands accordingly."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, Tuple[int, Tuple[str, ...]]] = {}
    for lineno, row in _table_rows(text):
        cells = [c.strip() for c in row.strip("|").split("|")]
        if not cells:
            continue
        for span in _BACKTICK_RE.findall(cells[0]):
            m = _METRIC_TOKEN_RE.match(span.strip())
            if not m:
                continue
            labels: List[str] = []
            for lab in (m.group(3) or "").split(","):
                lab = lab.strip()
                if lab == "eng":
                    labels.extend(("engine_id", "model_id"))
                elif lab:
                    labels.append(lab)
            out.setdefault(m.group(1), (lineno, tuple(labels)))
    return out


def parse_fault_doc(path: str) -> Dict[str, int]:
    """{fault_point: lineno} from the first cell of catalog table rows."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, int] = {}
    for lineno, row in _table_rows(text):
        cells = [c.strip() for c in row.strip("|").split("|")]
        if not cells:
            continue
        for span in _BACKTICK_RE.findall(cells[0]):
            if _FAULT_TOKEN_RE.match(span.strip()):
                out.setdefault(span.strip(), lineno)
    return out


def parse_event_doc(path: str) -> Dict[str, int]:
    """{trace_event_name: lineno} from the first cell of catalog table
    rows — the docs/OBSERVABILITY.md event-name table (TPL010)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    out: Dict[str, int] = {}
    for lineno, row in _table_rows(text):
        cells = [c.strip() for c in row.strip("|").split("|")]
        if not cells:
            continue
        for span in _BACKTICK_RE.findall(cells[0]):
            if _EVENT_TOKEN_RE.match(span.strip()):
                out.setdefault(span.strip(), lineno)
    return out


# ----------------------------------------------------------------- code side
@dataclass(frozen=True)
class MetricRegistration:
    name: Optional[str]        # None when the name isn't a literal
    kind: str                  # counter / gauge / histogram / bridge-gauge
    labels: Optional[Tuple[str, ...]]   # None when not statically known
    relpath: str
    line: int


@dataclass(frozen=True)
class FaultSite:
    name: str
    kind: str                  # point / declare_point / inject
    relpath: str
    line: int


@dataclass(frozen=True)
class TraceEmit:
    name: str
    relpath: str
    line: int


def _is_registry_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return bool(_REGISTRY_RECEIVER_RE.match(node.id))
    if isinstance(node, ast.Attribute):
        # self._registry / metrics.registry style
        return bool(_REGISTRY_RECEIVER_RE.match(node.attr))
    if isinstance(node, ast.Call):
        tail = dotted_name(node.func)
        return bool(tail and tail.split(".")[-1] == "get_registry")
    return False


def _literal_labels(call: ast.Call) -> Optional[Tuple[str, ...]]:
    """The ``labels=`` keyword as a tuple of strings, () when absent,
    None when present but not a literal (e.g. ``labels=_eng``)."""
    for kw in call.keywords:
        if kw.arg == "labels":
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in v.elts):
                return tuple(e.value for e in v.elts)
            return None
    return ()


def registration_of(call: ast.Call, relpath: str) -> \
        Optional[MetricRegistration]:
    """The MetricRegistration described by ``call``, or None when the
    call isn't a registry declaration."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in (
            "counter", "gauge", "histogram"):
        if not _is_registry_receiver(func.value) or not call.args:
            return None
        first = call.args[0]
        name = (first.value if isinstance(first, ast.Constant)
                and isinstance(first.value, str) else None)
        return MetricRegistration(name=name, kind=func.attr,
                                  labels=_literal_labels(call),
                                  relpath=relpath, line=call.lineno)
    tail = dotted_name(func)
    if tail and tail.split(".")[-1] == "record_counter" and call.args:
        first = call.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return MetricRegistration(
                name=sanitize_metric_name(first.value), kind="bridge-gauge",
                labels=(), relpath=relpath, line=call.lineno)
    return None


def collect_metric_registrations(tree: ast.Module,
                                 relpath: str) -> List[MetricRegistration]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            reg = registration_of(node, relpath)
            if reg is not None:
                out.append(reg)
    return out


def collect_label_uses(tree: ast.Module) -> List[Tuple[ast.Call,
                                                       Optional[str]]]:
    """Every ``<receiver>.labels(...)`` call with the receiver's dotted
    name — TPL003 cross-checks the keywords against the declaration the
    receiver was assigned from. A Call receiver (the chained
    ``reg.counter(...).labels(...)`` one-liner) has no dotted name and
    is yielded with recv=None; the rule resolves it directly from the
    chained registration."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "labels"):
            recv = dotted_name(node.func.value)
            if recv is not None or isinstance(node.func.value, ast.Call):
                out.append((node, recv))
    return out


_FAULT_FUNCS = {"point", "declare_point", "inject"}


def collect_fault_sites(tree: ast.Module, relpath: str) -> List[FaultSite]:
    """Literal fault-point names at ``faults.point/declare_point/inject``
    call sites. Bare names (``point(...)``) count only when the module
    imported them from the faults package — a module defining its own
    ``point()`` is not a fault site."""
    bare_ok = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
                node.module.endswith("faults")
                or node.module.endswith("injection")
                or node.module == "faults"):
            for alias in node.names:
                if alias.name in _FAULT_FUNCS:
                    bare_ok.add(alias.asname or alias.name)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        kind = None
        if (isinstance(func, ast.Attribute) and func.attr in _FAULT_FUNCS
                and isinstance(func.value, ast.Name)
                and func.value.id == "faults"):
            kind = func.attr
        elif isinstance(func, ast.Name) and func.id in bare_ok:
            kind = func.id
        if kind is None:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            out.append(FaultSite(name=first.value, kind=kind,
                                 relpath=relpath, line=node.lineno))
    return out


def _is_tracer_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return bool(_TRACER_RECEIVER_RE.match(node.id))
    if isinstance(node, ast.Attribute):
        # self._trace / tracing_module.tracer style — the TAIL decides,
        # so a bare ``self.emit(...)`` (the ONNX builder) never matches
        return bool(_TRACER_RECEIVER_RE.match(node.attr))
    if isinstance(node, ast.Call):
        tail = dotted_name(node.func)
        return bool(tail and tail.split(".")[-1] == "get_tracer")
    return False


def collect_trace_emits(tree: ast.Module, relpath: str) -> List[TraceEmit]:
    """Literal trace-event names at tracer ``.emit(...)`` call sites
    (see the module docstring's trace-emit grammar)."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"
                and node.args
                and _is_tracer_receiver(node.func.value)):
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                out.append(TraceEmit(name=first.value, relpath=relpath,
                                     line=node.lineno))
    return out
