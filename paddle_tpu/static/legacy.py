"""static facade tail — legacy program-manipulation API.

Reference parity: the remainder of ``python/paddle/static/__all__`` —
append_backward/gradients (fluid/backward.py), scope_guard/name_scope,
CompiledProgram/BuildStrategy/ExecutionStrategy (program wrappers whose
graph passes XLA performs), Print/py_func, WeightNormParamAttr,
ExponentialMovingAverage, serialize/deserialize + save/load of programs.
The Ipu* entries are deliberately absent: IPU hardware support is not a
capability of this TPU framework (a loud ImportError beats a stub).
"""
from __future__ import annotations

import pickle
from contextlib import contextmanager
from typing import Optional

import numpy as np

__all__ = [
    "append_backward", "gradients", "scope_guard", "name_scope",
    "BuildStrategy", "ExecutionStrategy", "CompiledProgram", "Print",
    "py_func", "WeightNormParamAttr", "ExponentialMovingAverage",
    "save", "load", "save_to_file", "load_from_file",
    "serialize_program", "serialize_persistables", "deserialize_program",
    "deserialize_persistables", "set_program_state", "normalize_program",
    "Variable", "create_global_var", "create_parameter", "device_guard",
    "load_program_state", "accuracy", "auc", "exponential_decay",
    "ctr_metric_bundle",
]


# ------------------------------------------------------------- autograd


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Record grads for a declarative loss (reference: fluid/backward.py
    append_backward). In this build the tape IS the program: running
    backward materializes grads on the parameters; returns
    [(param, grad)] like the reference."""
    loss.backward(retain_graph=True)
    params = parameter_list
    if params is None:
        from paddle_tpu.static import _collect_parameters

        params = _collect_parameters(loss)
    return [(p, p.grad) for p in params if p.grad is not None]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Symbolic-style grads of targets w.r.t. inputs (reference:
    static/gradients → paddle.grad under the hood here)."""
    from ..autograd import grad

    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


# ------------------------------------------------------------- scoping


class _Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


_scope_stack = [_Scope()]


@contextmanager
def scope_guard(scope):
    """reference: static/scope_guard — variable scope isolation."""
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


_name_scope_stack = []


@contextmanager
def name_scope(prefix: str = None):
    """reference: static/name_scope — op-name prefixes for debugging."""
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


# ----------------------------------------------------- program wrappers


class BuildStrategy:
    """Graph-build options (reference: BuildStrategy over the SSA graph
    passes). XLA performs fusion/memory passes; the knobs are recorded
    so reference configs parse."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.build_cuda_graph = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """reference: CompiledProgram — a Program + build strategy; execution
    still goes through Executor (which jits either way)."""

    def __init__(self, program, build_strategy: Optional[BuildStrategy] = None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        if item == "_program":  # absent during unpickling: avoid recursion
            raise AttributeError(item)
        return getattr(self._program, item)


# ------------------------------------------------------------ debug ops


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Host-side tensor print (reference: Print op). Eagerly prints and
    returns the input (identity in the graph)."""
    import jax

    from ..tensor import Tensor

    def cb(v):
        head = message or "Print"
        print(f"{head}: shape={list(v.shape)} dtype={v.dtype}")
        flat = np.asarray(v).reshape(-1)
        if summarize >= 0:
            flat = flat[:summarize]
        print(f"  data: {flat}")
        return v

    t = input if isinstance(input, Tensor) else Tensor(input)
    if hasattr(t._value, "addressable_shards") or not isinstance(
            t._value, jax.core.Tracer):
        cb(jax.device_get(t._value))
        return t
    # under trace: host callback keeps the print in the compiled program
    from ..autograd.engine import apply_op

    def fn(v):
        jax.debug.callback(cb, v)
        return v

    return apply_op(fn, [t], name="print")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Run a python function inside the program (reference: py_func op).
    Eager execution calls it directly; under jit it becomes a
    jax.pure_callback with the declared output spec."""
    import jax

    from ..autograd.engine import apply_op
    from ..ops._apply import ensure_tensor
    from ..tensor import Tensor

    xs = [ensure_tensor(t) for t in (x if isinstance(x, (list, tuple))
                                     else [x])]
    out_spec = out

    def fn(*vals):
        if any(isinstance(v, jax.core.Tracer) for v in vals):
            specs = (out_spec if isinstance(out_spec, (list, tuple))
                     else [out_spec])
            jspecs = [jax.ShapeDtypeStruct(tuple(sp.shape), sp.dtype)
                      for sp in specs]

            def host(*a):
                res = func(*[Tensor(np.asarray(x_)) for x_ in a])
                outs = res if isinstance(res, (list, tuple)) else [res]
                return [np.asarray(o.numpy() if isinstance(o, Tensor)
                                   else o) for o in outs]

            out = jax.pure_callback(host, jspecs, *vals)
            return out if isinstance(out_spec, (list, tuple)) else out[0]
        res = func(*[Tensor(v) for v in vals])
        if isinstance(res, (list, tuple)):
            return [o._value if isinstance(o, Tensor) else o for o in res]
        return res._value if isinstance(res, Tensor) else res

    return apply_op(fn, xs, name="py_func")


# ------------------------------------------------------------- training


class WeightNormParamAttr:
    """reference: static/WeightNormParamAttr — ParamAttr triggering weight
    normalization; maps onto nn.utils.weight_norm in this build."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable


class ExponentialMovingAverage:
    """EMA of parameters (reference: static/ExponentialMovingAverage):
    ``update()`` after each step; ``apply()`` context swaps EMA weights
    in for evaluation; ``restore()`` undoes."""

    def __init__(self, decay: float = 0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._ema: dict = {}
        self._backup: dict = {}
        self._params: list = []
        self._step = 0

    def _track(self, params):
        for p in params:
            if p._uid not in self._ema:
                self._params.append(p)
                self._ema[p._uid] = p._value

    def update(self, parameters=None):
        import jax.numpy as jnp

        if parameters is not None:
            self._track(parameters)
        self._step += 1
        # reference (_get_ema_decay): the (1+t)/(10+t) warm-up ramp only
        # applies when thres_steps is given; plain EMA uses decay as-is
        if self._thres_steps is not None:
            d = min(self._decay, (1 + self._step) / (10 + self._step))
        else:
            d = self._decay
        for p in self._params:
            self._ema[p._uid] = (d * self._ema[p._uid]
                                 + (1.0 - d) * p._value)

    @contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[p._uid] = p._value
            p._set_value(self._ema[p._uid])
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if p._uid in self._backup:
                p._set_value(self._backup.pop(p._uid))


# ------------------------------------------------- program serialization


class Variable:
    """Lightweight named value descriptor (reference: framework Variable;
    here the placeholders created by static.data serve the role — this
    class types them for isinstance checks in ported code)."""

    def __init__(self, name, shape=None, dtype=None):
        self.name = name
        self.shape = shape
        self.dtype = dtype


def serialize_program(feed_vars, fetch_vars, **kwargs) -> bytes:
    """Program metadata → bytes (reference: static/io.py
    serialize_program — derives the program from the passed vars, not
    the ambient default). The compiled-artifact form of a program is
    save_inference_model's StableHLO file; this serializes the feed
    interface the way the reference serializes the ProgramDesc."""
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    spec = {getattr(v, "name", f"x{i}"): {
                "shape": list(getattr(v, "shape", [])),
                "dtype": str(getattr(v, "dtype", "float32"))}
            for i, v in enumerate(feeds)}
    return pickle.dumps({"program": spec})


def serialize_persistables(feed_vars, fetch_vars, **kwargs) -> bytes:
    """Parameters reachable from the FETCH vars' tape (not whatever
    program happens to be the ambient default)."""
    import numpy as np_

    from . import _collect_parameters_multi

    fetches = (fetch_vars if isinstance(fetch_vars, (list, tuple))
               else [fetch_vars])
    params = _collect_parameters_multi(fetches, trainable_only=False)
    return pickle.dumps({
        (getattr(p, "name", None) or f"param_{i}"): np_.asarray(p._value)
        for i, p in enumerate(params)})


def deserialize_program(data: bytes):
    return pickle.loads(data)["program"]


def deserialize_persistables(program, data: bytes, executor=None):
    return pickle.loads(data)


def save_to_file(path: str, content: bytes) -> None:
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def save(program, model_prefix: str, protocol: int = 4) -> None:
    """reference: static/save — program + parameters to <prefix>.pdmodel/
    .pdiparams (parameters via the tape's state snapshot)."""
    state = program._param_state() if hasattr(program, "_param_state") else {}
    with open(model_prefix + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_prefix: str, executor=None, var_list=None) -> None:
    with open(model_prefix + ".pdparams", "rb") as f:
        state = pickle.load(f)
    if hasattr(program, "_set_param_state"):
        program._set_param_state(state)


def set_program_state(program, state_dict) -> None:
    if hasattr(program, "_set_param_state"):
        program._set_param_state(state_dict)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference: static/normalize_program — prune to the feed→fetch
    subgraph; the recorded placeholder graph is already minimal."""
    return program


# ------------------------------------------------------------ var helpers


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: static/create_global_var — a persistent filled tensor."""
    import jax.numpy as jnp

    from ..dtypes import convert_dtype
    from ..tensor import Tensor

    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        convert_dtype(dtype)), stop_gradient=False)
    if name:
        t.name = name
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..framework.core_api import create_parameter as _cp

    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


@contextmanager
def device_guard(device=None):
    """reference: static/device_guard — pin ops to a device. TPU build:
    'cpu' pins to host, anything else stays on the default device."""
    import jax

    if device and str(device).startswith("cpu"):
        with jax.default_device(jax.devices("cpu")[0]):
            yield
    else:
        yield


def load_program_state(model_path, var_list=None):
    """reference: static/load_program_state — read a saved param state."""
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (reference: static/auc). Returns the AUC over this
    batch's predictions (stateful accumulation lives in metric.Auc)."""
    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    import numpy as np_

    m.update(np.asarray(input.numpy() if hasattr(input, "numpy") else input),
             np_.asarray(label.numpy() if hasattr(label, "numpy")
                         else label))
    from ..tensor import Tensor
    import jax.numpy as jnp

    return Tensor(jnp.asarray(m.accumulate(), jnp.float64))


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """reference: fluid/layers exponential_decay → an LR scheduler."""
    from ..optimizer.lr import ExponentialDecay

    del decay_steps, staircase  # per-epoch semantics in the LR API
    return ExponentialDecay(learning_rate=learning_rate, gamma=decay_rate)


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference: static/ctr_metric_bundle — (auc, precision-ish bundle)
    for CTR models; returns (auc, sqrerr, abserr, prob, q, pos, total)."""
    import numpy as np_

    from ..tensor import Tensor
    import jax.numpy as jnp

    pred = np_.asarray(input.numpy() if hasattr(input, "numpy") else input
                       ).reshape(-1)
    lab = np_.asarray(label.numpy() if hasattr(label, "numpy") else label
                      ).reshape(-1)
    sqrerr = float(((pred - lab) ** 2).sum())
    abserr = float(np_.abs(pred - lab).sum())
    q = float(pred.sum())
    pos = float(lab.sum())
    total = float(lab.size)
    auc_v = float(np_.asarray(auc(Tensor(jnp.asarray(pred)),
                                  Tensor(jnp.asarray(lab.astype("int64")))
                                  ).numpy()))
    mk = lambda v: Tensor(jnp.asarray(v))
    return (mk(auc_v), mk(sqrerr), mk(abserr), mk(q / max(total, 1)),
            mk(q), mk(pos), mk(total))
