"""Data-dependent control flow: ``cond`` / ``while_loop`` / ``case`` /
``switch_case``.

Reference parity: python/paddle/static/nn/control_flow.py (``while_loop``
:401, ``case`` :564, ``switch_case`` :697, ``cond`` :873), which lower to the
``conditional_block`` / ``while`` ops plus a merge pass over block
inputs/outputs.

TPU-native redesign: the reference builds sub-blocks in a Program and an
interpreter executes the taken branch; gradients need hand-written
``conditional_block_grad`` / ``while_grad`` ops with a tensor stack. Here the
branches lower straight to XLA's structured control flow —
``lax.cond`` / ``lax.switch`` / ``lax.while_loop`` — and reverse-mode AD
through ``cond``/``switch`` comes from jax's AD of those primitives, recorded
on the eager tape as ONE op via ``apply_op``.

Two execution regimes, mirroring the reference's dygraph/static split:

- **Concrete predicate** (eager): run the chosen branch directly in Python —
  exactly the reference's dygraph fast path (control_flow.py:931). The tape
  records the branch's ops; gradients flow with no special casing, including
  through data-dependent ``while_loop`` trip counts.
- **Traced predicate** (under ``jit.to_static`` / ``StaticFunction``): the
  branch callables close over outer tensors, so we first run a *capture
  discovery* pass (the block-input analysis the reference does on its
  sub-block var reads) using a tape observer, then re-trace each branch as a
  pure jax function of the captured arrays inside the lax primitive.

``while_loop`` under a traced predicate compiles via ``lax.while_loop`` and
is forward-only: reverse-mode through an unbounded data-dependent loop needs
an activation stack (the reference's ``while_grad``), which XLA's static
memory model does not express. The eager regime differentiates it fully.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...autograd import engine as _engine
from ...autograd.engine import apply_op, no_grad
from ...tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


# --------------------------------------------------------------- helpers

def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _pred_value(pred):
    """Concrete bool of an eager predicate (shape () or (1,))."""
    v = pred._value if isinstance(pred, Tensor) else pred
    return bool(np.asarray(v).reshape(()))


def _pred_array(pred):
    v = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
    return jnp.reshape(v, ()).astype(jnp.bool_)


def _leaf_value(leaf):
    return leaf._value if isinstance(leaf, Tensor) else jnp.asarray(leaf)


class _CaptureObserver:
    """Records every pre-existing tensor an op inside the branch reads.

    Tensors are monotonically uid-stamped; anything at or below the watermark
    existed before the branch ran and is therefore an external capture (a
    "block input" in reference terms). ``exclude`` holds explicit operands
    (loop vars) that must not be double-captured.
    """

    def __init__(self, watermark: int, exclude: frozenset = frozenset()):
        self.watermark = watermark
        self.exclude = exclude
        self.external: dict = {}  # id(t) -> Tensor, insertion-ordered

    def __call__(self, tensors):
        for t in tensors:
            if (t._uid <= self.watermark and id(t) not in self.exclude
                    and id(t) not in self.external):
                self.external[id(t)] = t

    def add_output(self, leaf):
        if (isinstance(leaf, Tensor) and leaf._uid <= self.watermark
                and id(leaf) not in self.exclude
                and id(leaf) not in self.external):
            self.external[id(leaf)] = leaf


def _discover(fn: Callable, args: Sequence[Tensor] = (),
              exclude: Sequence[Tensor] = ()):
    """Run ``fn(*args)`` once eagerly (no tape nodes) while recording which
    pre-existing tensors it reads. Returns (output, captures)."""
    watermark = Tensor(jnp.zeros(()))._uid
    obs = _CaptureObserver(watermark, frozenset(id(t) for t in exclude))
    _engine._op_input_observers.append(obs)
    try:
        with no_grad():
            out = fn(*args)
    finally:
        _engine._op_input_observers.remove(obs)
    flat, _ = jax.tree_util.tree_flatten(out)
    for leaf in flat:  # identity branches return captures without any op
        obs.add_output(leaf)
    return out, list(obs.external.values())


def _run_substituted(fn: Callable, ext: List[Tensor], ext_vals,
                     args: Sequence[Tensor] = (), arg_tensors=(),
                     arg_vals=(), extract: Callable = None):
    """Re-run ``fn`` as a pure function: temporarily swap the captured (and
    loop-var) tensors' payloads for the supplied trace values, execute under
    no_grad, restore. Single-threaded by construction (one tape).

    ``extract`` runs on the output INSIDE the swapped state — required
    whenever the caller reads tensor payloads from the result: a body that
    returns one of the substituted tensor OBJECTS (e.g. a while body
    passing a carry arg through to a different output slot) would
    otherwise have its payload restored to the stale pre-swap value before
    the caller looks at it (r4 bug: the for-range loop target read back
    as its seed)."""
    swap = list(zip(ext, ext_vals)) + list(zip(arg_tensors, arg_vals))
    olds = [t._value for t, _ in swap]
    for t, v in swap:
        t._value = v
    try:
        with no_grad():
            out = fn(*args)
            return extract(out) if extract is not None else out
    finally:
        for (t, _), old in zip(swap, olds):
            t._value = old


def _flat_struct(out):
    """(treedef, leaf avals) used to validate branch agreement."""
    flat, treedef = jax.tree_util.tree_flatten(out)
    vals = [_leaf_value(v) for v in flat]
    return treedef, [(v.shape, jnp.result_type(v)) for v in vals]


def _traced_multiway(selector, fns: Sequence[Callable], name: str):
    """Lower ``fns[selector]()`` to ``lax.switch`` (N=2 → ``lax.cond``) with
    capture discovery; grads flow to the captures via jax AD through the
    primitive, recorded as one tape op."""
    outs, caps, structs = [], [], []
    for fn in fns:
        o, c = _discover(fn)
        outs.append(o)
        caps.append(c)
        structs.append(_flat_struct(o))
    treedef, avals = structs[0]
    for i, (td, av) in enumerate(structs[1:], start=1):
        if td != treedef or av != avals:
            raise ValueError(
                f"{name}: branch 0 and branch {i} must return the same "
                f"structure/shapes/dtypes; got {treedef}/{avals} vs {td}/{av}"
                " (reference raises the same constraint for merged block "
                "outputs)")

    ext: List[Tensor] = []
    seen = set()
    for c in caps:
        for t in c:
            if id(t) not in seen:
                seen.add(id(t))
                ext.append(t)

    sel = selector if _is_traced(selector) else jnp.asarray(selector)

    def pure(*ext_arrays):
        def make_branch(fn):
            def br(ops):
                def ex(out):
                    flat, _ = jax.tree_util.tree_flatten(out)
                    return tuple(_leaf_value(v) for v in flat)
                return _run_substituted(fn, ext, ops, extract=ex)
            return br

        branches = [make_branch(fn) for fn in fns]
        return jax.lax.switch(sel, branches, tuple(ext_arrays))

    n_leaves = treedef.num_leaves
    if n_leaves == 0:
        # both branches return None/empty — still execute for parity
        pure(*[t._value for t in ext])
        return jax.tree_util.tree_unflatten(treedef, [])
    res = apply_op(pure, ext, name=name)
    res = res if isinstance(res, tuple) else (res,)
    return jax.tree_util.tree_unflatten(treedef, list(res))


# ------------------------------------------------------------------ cond

def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name: Optional[str] = None,
         return_names=None):
    """reference: static/nn/control_flow.py:873. Runs ``true_fn()`` when
    ``pred`` holds else ``false_fn()``; both must return the same structure.

    Concrete ``pred`` runs the chosen branch on the tape (dygraph regime);
    traced ``pred`` lowers to ``lax.cond`` with differentiable captures.
    """
    if true_fn is None and false_fn is None:
        return None
    true_fn = true_fn if true_fn is not None else (lambda: None)
    false_fn = false_fn if false_fn is not None else (lambda: None)
    if not callable(true_fn) or not callable(false_fn):
        raise TypeError("cond: true_fn and false_fn must be callable")

    pv = pred._value if isinstance(pred, Tensor) else pred
    if not _is_traced(pv):
        return true_fn() if _pred_value(pred) else false_fn()
    # lax.switch selector: 0 → false, 1 → true
    sel = jnp.reshape(pv, ()).astype(jnp.int32)
    return _traced_multiway(sel, [false_fn, true_fn], name or "cond")


# ------------------------------------------------------------ while_loop

def while_loop(cond, body, loop_vars, is_test: bool = False,
               name: Optional[str] = None):
    """reference: static/nn/control_flow.py:401. Repeats ``body(*loop_vars)``
    while ``cond(*loop_vars)`` holds; returns the final loop vars.

    Concrete predicate: a Python loop on the tape — fully differentiable
    with a data-dependent trip count (the dygraph regime). Traced predicate:
    ``lax.while_loop`` — compiled, forward-only (see module docstring).
    """
    if not callable(cond) or not callable(body):
        raise TypeError("while_loop: cond and body must be callable")
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("while_loop: loop_vars must be a non-empty "
                         "list/tuple")
    loop_vars = list(loop_vars)

    first = cond(*loop_vars)
    fv = first._value if isinstance(first, Tensor) else first
    if not _is_traced(fv):
        # eager regime — reference dygraph path (control_flow.py:520)
        while _pred_value(first):
            out = body(*loop_vars)
            out = list(out) if isinstance(out, (list, tuple)) else [out]
            if len(out) != len(loop_vars):
                raise ValueError(
                    f"while_loop: body returned {len(out)} vars, expected "
                    f"{len(loop_vars)}")
            loop_vars = out
            first = cond(*loop_vars)
        return loop_vars

    # traced regime — compile to lax.while_loop
    flat_lv, lv_tree = jax.tree_util.tree_flatten(loop_vars)
    for v in flat_lv:
        if not isinstance(v, Tensor):
            raise TypeError(
                "while_loop under trace: every loop_vars leaf must be a "
                f"Tensor (got {type(v).__name__}) — a Python scalar would "
                "compile to a constant, not a carried value")
    # FRESH carry cells: an initial loop var may be identity-aliased with
    # a tensor the body ALSO reads through its closure (`s = x` before the
    # loop, then `s + x` inside it). Carry substitution swaps the shared
    # cell's payload, silently turning the closure read into the carry
    # (`s + x` became `s + s`, measured r5). With fresh cells the aliased
    # closure read is discovered as a normal capture and keeps its own
    # value — matching the eager regime, where the cell is never mutated.
    flat_lv = [Tensor(t._value, stop_gradient=t.stop_gradient)
               for t in flat_lv]
    loop_vars = jax.tree_util.tree_unflatten(lv_tree, flat_lv)
    lv_tensors = list(flat_lv)
    _, cap_c = _discover(cond, args=loop_vars, exclude=lv_tensors)
    body_out, cap_b = _discover(body, args=loop_vars, exclude=lv_tensors)
    out_flat, out_tree = jax.tree_util.tree_flatten(
        list(body_out) if isinstance(body_out, (list, tuple)) else [body_out])
    if len(out_flat) != len(flat_lv):
        raise ValueError(
            f"while_loop: body returned {len(out_flat)} leaves, expected "
            f"{len(flat_lv)} (must match loop_vars structure)")

    ext: List[Tensor] = []
    seen = set()
    for c in (cap_c, cap_b):
        for t in c:
            if id(t) not in seen:
                seen.add(id(t))
                ext.append(t)
    n = len(lv_tensors)

    def pure(*arrays):
        lv0, ext_arrays = arrays[:n], arrays[n:]

        def c_fn(carry):
            return _run_substituted(
                cond, ext, ext_arrays, args=loop_vars,
                arg_tensors=lv_tensors, arg_vals=carry,
                extract=lambda out: jnp.reshape(
                    _leaf_value(out), ()).astype(jnp.bool_))

        def b_fn(carry):
            def ex(out):
                out = list(out) if isinstance(out, (list, tuple)) else [out]
                flat, _ = jax.tree_util.tree_flatten(out)
                return tuple(_leaf_value(v) for v in flat)
            return _run_substituted(body, ext, ext_arrays, args=loop_vars,
                                    arg_tensors=lv_tensors, arg_vals=carry,
                                    extract=ex)

        return jax.lax.while_loop(c_fn, b_fn, tuple(lv0))

    # XLA's while has no reverse-mode; outputs are detached from the tape
    res = apply_op(pure, lv_tensors + ext, name=name or "while_loop",
                   differentiable=False)
    res = res if isinstance(res, tuple) else (res,)
    return jax.tree_util.tree_unflatten(lv_tree, list(res))


# ------------------------------------------------------------------ case

def case(pred_fn_pairs, default: Optional[Callable] = None,
         name: Optional[str] = None):
    """reference: static/nn/control_flow.py:564. Runs the fn of the FIRST
    true predicate; ``default`` (or the last pair's fn) when none hold."""
    if not isinstance(pred_fn_pairs, (list, tuple)) or not pred_fn_pairs:
        raise TypeError("case: pred_fn_pairs must be a non-empty list/tuple")
    pairs = []
    for item in pred_fn_pairs:
        if not isinstance(item, (list, tuple)) or len(item) != 2:
            raise TypeError(f"case: each entry must be a (pred, fn) pair, "
                            f"got {item!r}")
        p, f = item
        if not callable(f):
            raise TypeError("case: fn must be callable")
        pairs.append((p, f))
    if default is None:
        default = pairs[-1][1]  # reference: last fn doubles as default
        pairs = pairs[:-1]
        if not pairs:
            return default()
    elif not callable(default):
        raise TypeError("case: default must be callable")

    pred_vals = [p._value if isinstance(p, Tensor) else p for p, _ in pairs]
    if not any(_is_traced(v) for v in pred_vals):
        for (p, f) in pairs:
            if _pred_value(p):
                return f()
        return default()

    # traced: selector = index of first true predicate, else the default slot
    stacked = jnp.stack([jnp.reshape(v, ()).astype(jnp.bool_)
                         for v in pred_vals])
    first_true = jnp.argmax(stacked).astype(jnp.int32)
    sel = jnp.where(jnp.any(stacked), first_true, len(pairs))
    return _traced_multiway(sel, [f for _, f in pairs] + [default],
                            name or "case")


# ----------------------------------------------------------- switch_case

def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name: Optional[str] = None):
    """reference: static/nn/control_flow.py:697. Runs the branch whose index
    equals ``branch_index``; ``default`` (or the max-index fn) otherwise."""
    if isinstance(branch_fns, dict):
        items = list(branch_fns.items())
    elif isinstance(branch_fns, (list, tuple)):
        if branch_fns and callable(branch_fns[0]):
            items = list(enumerate(branch_fns))
        else:
            items = [tuple(it) for it in branch_fns]
    else:
        raise TypeError("switch_case: branch_fns must be a list/tuple/dict")
    keys = [int(k) for k, _ in items]
    if len(set(keys)) != len(keys):
        raise ValueError(f"switch_case: duplicate branch index in {keys}")
    items = sorted(((int(k), f) for k, f in items), key=lambda kv: kv[0])
    for _, f in items:
        if not callable(f):
            raise TypeError("switch_case: every branch fn must be callable")
    if default is None:
        default = items[-1][1]  # reference: max-index fn is the default
    elif not callable(default):
        raise TypeError("switch_case: default must be callable")

    bi = branch_index._value if isinstance(branch_index, Tensor) \
        else branch_index
    if not _is_traced(bi):
        key = int(np.asarray(bi).reshape(()))
        for k, f in items:
            if k == key:
                return f()
        return default()

    bi = jnp.reshape(bi, ()).astype(jnp.int32)
    sel = jnp.asarray(len(items), jnp.int32)  # default slot
    for pos, (k, _) in enumerate(items):
        sel = jnp.where(bi == k, pos, sel)
    return _traced_multiway(sel, [f for _, f in items] + [default],
                            name or "switch_case")
