"""Sequence (legacy LoD) ops, dense TPU redesign.

Reference parity: python/paddle/static/nn/sequence_lod.py — the reference
operates on LoD (ragged level-of-detail) tensors whose row offsets live
host-side. Ragged shapes defeat XLA's static tiling, so the TPU-native
redesign uses the padded-dense convention the rest of this framework (and
modern paddle itself) uses: a sequence batch is ``[B, T, ...]`` with time on
axis 1, optional per-row ``length`` tensors where the reference consumed LoD
offsets, and masking instead of ragged storage. Each function documents the
reference op it covers.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...ops._apply import apply_op, ensure_tensor
from ...nn.initializer import Constant, XavierNormal
from ...tensor import Tensor
from ..legacy import create_parameter

__all__ = [
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_reverse",
]


def sequence_conv(input, num_filters: int, filter_size: int = 3,
                  filter_stride: int = 1, padding: bool = True,
                  padding_start: Optional[int] = None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """reference: sequence_lod.py sequence_conv — context-window conv over
    time: im2col the window then one MXU matmul."""
    x = ensure_tensor(input)
    D = x.shape[-1]
    k = int(filter_size)
    start = -((k - 1) // 2) if padding_start is None else int(padding_start)
    w = create_parameter([k * D, num_filters], x.dtype, attr=param_attr,
                         default_initializer=XavierNormal())
    b = None if bias_attr is False else create_parameter(
        [num_filters], x.dtype, attr=bias_attr, is_bias=True,
        default_initializer=Constant(0.0))
    ins = [x, w] + ([b] if b is not None else [])

    def sc(v, wv, *rest):
        # window for output t is input rows {t+start, ..., t+start+k-1};
        # pad both ends so every tap indexes in-bounds, then slice with the
        # start offset folded in
        T = v.shape[1]
        lo, hi = max(0, -start), max(0, start + k - 1)
        vp = jnp.pad(v, ((0, 0), (lo, hi), (0, 0)))
        cols = jnp.concatenate(
            [vp[:, lo + start + i:lo + start + i + T] for i in range(k)],
            axis=-1)  # [B, T, k*D]
        out = cols @ wv
        return out + rest[0] if rest else out

    out = apply_op(sc, ins, name="sequence_conv")
    if act is not None:
        from ...nn import functional as F
        out = getattr(F, act)(out)
    return out


def sequence_softmax(input, use_cudnn: bool = False, name=None):
    """reference: sequence_lod.py sequence_softmax — softmax within each
    sequence (dense: over the time axis)."""
    from ...nn import functional as F

    x = ensure_tensor(input)
    return F.softmax(x, axis=1 if x.ndim > 1 else 0)


def sequence_pool(input, pool_type: str, is_test: bool = False,
                  pad_value: float = 0.0):
    """reference: sequence_lod.py sequence_pool — average/sum/sqrt/max/
    last/first over each sequence's time steps."""
    x = ensure_tensor(input)
    pt = pool_type.lower()

    def pool(v):
        if pt == "average":
            return jnp.mean(v, axis=1)
        if pt == "sum":
            return jnp.sum(v, axis=1)
        if pt == "sqrt":
            return jnp.sum(v, axis=1) / np.sqrt(v.shape[1])
        if pt == "max":
            return jnp.max(v, axis=1)
        if pt == "last":
            return v[:, -1]
        if pt == "first":
            return v[:, 0]
        raise ValueError(f"sequence_pool: bad pool_type {pool_type!r}")

    return apply_op(pool, [x], name=f"sequence_pool_{pt}")


def sequence_concat(input, name=None):
    """reference: sequence_lod.py sequence_concat — joins sequences
    time-wise (dense: concat on axis 1)."""
    xs = [ensure_tensor(v) for v in input]
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=1), xs,
                    name="sequence_concat")


def sequence_first_step(input):
    """reference: sequence_lod.py sequence_first_step."""
    return apply_op(lambda v: v[:, 0], [ensure_tensor(input)],
                    name="sequence_first_step")


def sequence_last_step(input):
    """reference: sequence_lod.py sequence_last_step."""
    return apply_op(lambda v: v[:, -1], [ensure_tensor(input)],
                    name="sequence_last_step")


def sequence_slice(input, offset, length, name=None):
    """reference: sequence_lod.py sequence_slice — per-sequence sub-span.
    Dense: one shared (offset, length) span along time; scalar or
    per-row-equal tensors accepted (ragged spans don't tile on TPU)."""
    x = ensure_tensor(input)
    off = int(np.asarray(ensure_tensor(offset)._value).reshape(-1)[0])
    ln = int(np.asarray(ensure_tensor(length)._value).reshape(-1)[0])
    return apply_op(lambda v: v[:, off:off + ln], [x],
                    name="sequence_slice")


def sequence_expand(x, y, ref_level: int = -1, name=None):
    """reference: sequence_lod.py sequence_expand — repeat x's rows per y's
    LoD. Dense: broadcast x's time axis to y's time length."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)

    def exp(xv, yv):
        T = yv.shape[1]
        if xv.ndim == 2:
            xv = xv[:, None, :]
        if T % xv.shape[1]:
            raise ValueError(
                f"sequence_expand: y's time length {T} is not a multiple of "
                f"x's {xv.shape[1]} — a silent truncation here surfaces as a "
                "shape error far downstream")
        reps = [1] * xv.ndim
        reps[1] = T // xv.shape[1]
        return jnp.tile(xv, reps)

    return apply_op(exp, [xt, yt], name="sequence_expand")


def sequence_expand_as(x, y, name=None):
    """reference: sequence_lod.py sequence_expand_as."""
    return sequence_expand(x, y, name=name)


def sequence_pad(x, pad_value, maxlen: Optional[int] = None, name=None):
    """reference: sequence_lod.py sequence_pad — returns (padded, lengths).
    Dense input is already rectangular; pads time to ``maxlen``."""
    xt = ensure_tensor(x)
    pv = ensure_tensor(pad_value)
    T = xt.shape[1]
    target = int(maxlen) if maxlen is not None else T

    def pad(v, p):
        if target <= T:
            return v[:, :target]
        cfg = [(0, 0)] * v.ndim
        cfg[1] = (0, target - T)
        return jnp.pad(v, cfg, constant_values=p.reshape(()))

    padded = apply_op(pad, [xt, pv], name="sequence_pad")
    lengths = Tensor(jnp.full((xt.shape[0],), min(T, target), jnp.int64))
    return padded, lengths


def sequence_unpad(x, length, name=None):
    """reference: sequence_lod.py sequence_unpad — zero out positions past
    each row's length and trim to the longest row."""
    import jax

    xt, lt = ensure_tensor(x), ensure_tensor(length)
    max_len = xt.shape[1] if isinstance(lt._value, jax.core.Tracer) \
        else int(np.asarray(lt._value).max())

    def unpad(v, ln):
        pos = jnp.arange(v.shape[1])
        mask = pos[None, :] < ln.reshape(-1, 1)
        mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        return jnp.where(mask, v, 0)[:, :max_len]

    return apply_op(unpad, [xt, lt], name="sequence_unpad")


def sequence_reshape(input, new_dim: int, name=None):
    """reference: sequence_lod.py sequence_reshape — refold time×feature
    so the feature width becomes ``new_dim``."""
    x = ensure_tensor(input)
    return apply_op(lambda v: v.reshape(v.shape[0], -1, new_dim), [x],
                    name="sequence_reshape")


def sequence_scatter(input, index, updates, name=None):
    """reference: sequence_lod.py sequence_scatter — adds updates at the
    given time positions per row."""
    x, idx, upd = (ensure_tensor(input), ensure_tensor(index),
                   ensure_tensor(updates))

    def scat(v, iv, uv):
        iv = iv.reshape(v.shape[0], -1).astype(jnp.int32)
        uv = uv.reshape(iv.shape + v.shape[2:])
        rows = jnp.arange(v.shape[0])[:, None].repeat(iv.shape[1], axis=1)
        return v.at[rows, iv].add(uv)

    return apply_op(scat, [x, idx, upd], name="sequence_scatter")


def sequence_enumerate(input, win_size: int, pad_value: int = 0, name=None):
    """reference: sequence_lod.py sequence_enumerate — all length-
    ``win_size`` subsequences, padded at the tail."""
    x = ensure_tensor(input)

    def enum(v):
        T = v.shape[1]
        vp = jnp.pad(v, ((0, 0), (0, win_size - 1)),
                     constant_values=pad_value)
        return jnp.stack([vp[:, i:i + T] for i in range(win_size)], axis=-1)

    return apply_op(enum, [x], name="sequence_enumerate")


def sequence_reverse(x, name=None):
    """reference: sequence_lod.py sequence_reverse — flip time."""
    return apply_op(lambda v: jnp.flip(v, axis=1), [ensure_tensor(x)],
                    name="sequence_reverse")
