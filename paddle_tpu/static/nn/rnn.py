"""StaticRNN — build the step once, compile the time loop as ``lax.scan``.

Reference parity: python/paddle/fluid/layers (StaticRNN) — the reference
records the step body into a sub-block and a ``recurrent`` op's interpreter
walks it T times, with ``recurrent_grad`` replaying it backwards off an
activation stack.

TPU-native redesign: the user's ``with rnn.step():`` block executes ONCE
eagerly against step-0 slices, recording its ops on the tape. ``rnn()`` then
rebuilds that subgraph as a pure jax function (the same tape replay the
static Executor uses, incubate/autograd/_replay_function) and runs it under
``lax.scan`` over the time axis — one compiled XLA loop with sequence inputs
time-major ``[T, B, ...]``, memories as the scan carry, and full reverse-mode
AD through the scan (no hand-written grad op, no activation stack: XLA
rematerializes or saves per its own schedule).
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import List, Optional

import jax
import jax.numpy as jnp

from ...ops._apply import apply_op
from ...tensor import Tensor

__all__ = ["StaticRNN"]


class StaticRNN:
    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name: Optional[str] = None):
        self.name = name or "static_rnn"
        self.status = self.BEFORE_RNN
        self._seq: List[tuple] = []        # (full sequence Tensor, step ph)
        self._mems: List[list] = []        # [placeholder, init Tensor, new]
        self._outputs: List[Tensor] = []

    @contextmanager
    def step(self):
        if self.status != self.BEFORE_RNN:
            raise RuntimeError("StaticRNN.step() may only be entered once")
        self.status = self.IN_RNN
        # the scan body is REBUILT from the tape the step block records —
        # under no_grad (eval loops, onnx export) recording is off and the
        # replayed body would degenerate to step-0 constants (silently
        # broadcasting h0 over time); force recording for the block
        from ...autograd.engine import enable_grad

        try:
            with enable_grad():
                yield
        finally:
            self.status = self.AFTER_RNN

    def _require_in_rnn(self, what):
        if self.status != self.IN_RNN:
            raise RuntimeError(f"StaticRNN.{what} must be called inside "
                               "`with rnn.step():`")

    def step_input(self, x: Tensor) -> Tensor:
        """Register a time-major ``[T, B, ...]`` sequence; returns the
        per-step ``[B, ...]`` view the body computes on."""
        self._require_in_rnn("step_input")
        if self._seq and x.shape[0] != self._seq[0][0].shape[0]:
            raise ValueError("all StaticRNN step inputs must share sequence "
                             f"length; got {x.shape[0]} vs "
                             f"{self._seq[0][0].shape[0]}")
        ph = Tensor(x._value[0], stop_gradient=False)
        self._seq.append((x, ph))
        return ph

    def memory(self, init: Optional[Tensor] = None, shape=None,
               batch_ref: Optional[Tensor] = None, init_value: float = 0.0,
               init_batch_dim_idx: int = 0, ref_batch_dim_idx: int = 1):
        """A carried state; ``init`` tensor or zeros/[init_value] of
        ``shape`` with -1 resolved from ``batch_ref``'s batch dim."""
        self._require_in_rnn("memory")
        if init is not None:
            init_t = init if isinstance(init, Tensor) else Tensor(init)
        else:
            if shape is None or batch_ref is None:
                raise ValueError("StaticRNN.memory needs `init` or both "
                                 "`shape` and `batch_ref`")
            concrete = [batch_ref.shape[0] if int(d) < 0 else int(d)
                        for d in shape]
            init_t = Tensor(jnp.full(concrete, init_value,
                                     batch_ref._value.dtype))
        ph = Tensor(init_t._value, stop_gradient=False)
        self._mems.append([ph, init_t, None])
        return ph

    def update_memory(self, mem: Tensor, var: Tensor):
        self._require_in_rnn("update_memory")
        for rec in self._mems:
            if rec[0] is mem:
                rec[2] = var
                return
        raise ValueError("update_memory: unknown memory (pass the tensor "
                         "returned by StaticRNN.memory)")

    def step_output(self, o: Tensor):
        self._require_in_rnn("step_output")
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        if self.status != self.AFTER_RNN:
            raise RuntimeError("finish the `with rnn.step():` block before "
                               "calling the StaticRNN")
        if not self._outputs:
            raise ValueError("StaticRNN has no step_output")
        from ...incubate.autograd import _replay_function
        from .. import _collect_parameters_multi

        new_mems = [rec[2] if rec[2] is not None else rec[0]
                    for rec in self._mems]
        fetches = list(self._outputs) + new_mems
        seq_ph = [ph for _, ph in self._seq]
        mem_ph = [rec[0] for rec in self._mems]
        params = _collect_parameters_multi(fetches, trainable_only=False)
        fn, _ = _replay_function(fetches, seq_ph + mem_ph + params)

        n_seq, n_mem, n_out = len(seq_ph), len(mem_ph), len(self._outputs)

        def pure(*arrays):
            seqs = arrays[:n_seq]
            mem0 = arrays[n_seq:n_seq + n_mem]
            pvals = arrays[n_seq + n_mem:]

            def body(carry, xs):
                outs = fn(*xs, *carry, *pvals)
                outs = outs if isinstance(outs, tuple) else (outs,)
                return tuple(outs[n_out:]), tuple(outs[:n_out])

            _, ys = jax.lax.scan(body, tuple(mem0), tuple(seqs))
            return ys  # each [T, B, ...] time-major, reference layout

        ins = [x for x, _ in self._seq] + [rec[1] for rec in self._mems] \
            + params
        res = apply_op(pure, ins, name=self.name)
        res = res if isinstance(res, tuple) else (res,)
        return res[0] if len(res) == 1 else list(res)
