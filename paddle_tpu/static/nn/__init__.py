"""paddle.static.nn — static-graph layers + data-dependent control flow.

Reference parity: python/paddle/static/nn/__init__.py (__all__ at :63).
Layers create their own Parameters at build time (common.py), control flow
lowers to XLA's structured primitives (control_flow.py), sequence/LoD ops use
the padded-dense TPU convention (sequence_lod.py), StaticRNN compiles to
``lax.scan`` (rnn.py).
"""
from .common import (  # noqa: F401
    batch_norm, bilinear_tensor_product, continuous_value_model, conv2d,
    conv2d_transpose, conv3d, conv3d_transpose, data_norm, deform_conv2d,
    embedding, fc, group_norm, instance_norm, layer_norm, nce, prelu,
    py_func, row_conv, sparse_embedding, spectral_norm,
)
from .control_flow import case, cond, switch_case, while_loop  # noqa: F401
from .rnn import StaticRNN  # noqa: F401
from .sequence_lod import (  # noqa: F401
    sequence_concat, sequence_conv, sequence_enumerate, sequence_expand,
    sequence_expand_as, sequence_first_step, sequence_last_step,
    sequence_pad, sequence_pool, sequence_reshape, sequence_reverse,
    sequence_scatter, sequence_slice, sequence_softmax, sequence_unpad,
)
from ..legacy import create_parameter  # noqa: F401

__all__ = [
    'fc',
    'batch_norm',
    'bilinear_tensor_product',
    'embedding',
    'case',
    'cond',
    'conv2d',
    'conv2d_transpose',
    'conv3d',
    'conv3d_transpose',
    'data_norm',
    'deform_conv2d',
    'group_norm',
    'instance_norm',
    'layer_norm',
    'nce',
    'prelu',
    'py_func',
    'row_conv',
    'spectral_norm',
    'switch_case',
    'while_loop',
    'sparse_embedding',
    'sequence_conv',
    'sequence_softmax',
    'sequence_pool',
    'sequence_concat',
    'sequence_first_step',
    'sequence_last_step',
    'sequence_slice',
    'sequence_expand',
    'sequence_expand_as',
    'sequence_pad',
    'sequence_unpad',
    'sequence_reshape',
    'sequence_scatter',
    'sequence_enumerate',
    'sequence_reverse',
    'StaticRNN',
]
