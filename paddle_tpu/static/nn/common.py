"""Static-graph layer helpers: declarative layers that create their own
parameters at build time.

Reference parity: python/paddle/static/nn/common.py (``fc`` :28,
``batch_norm`` :1471, ``conv2d`` :399, ``embedding`` / ``sparse_embedding``,
``spectral_norm`` :2158, ``data_norm``, ``row_conv``, ``prelu``,
``bilinear_tensor_product``) and static/nn/loss.py (``nce``).

TPU-native collapse: the reference versions append OpDescs + parameter
VarDescs to the current Program's block. Here a "static layer" is a
build-time call that creates real ``Parameter`` cells (picked up by
``Optimizer.minimize`` via tape reachability, static/__init__.py
``_collect_parameters``) and records ordinary tape ops — the Program/block
bookkeeping collapses into the tape. Everything compiles under the
Executor's replay or ``jit.to_static``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...nn import functional as F
from ...nn.initializer import Constant, Normal, XavierNormal
from ...ops._apply import apply_op, ensure_tensor
from ...tensor import Parameter, Tensor
from ..legacy import py_func  # noqa: F401  (re-export; already static-shaped)
from ..legacy import create_parameter  # noqa: F401

__all__ = [
    "fc", "batch_norm", "instance_norm", "data_norm", "group_norm",
    "deform_conv2d", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "bilinear_tensor_product", "py_func", "row_conv",
    "spectral_norm", "prelu", "layer_norm", "embedding", "sparse_embedding",
    "continuous_value_model", "nce",
]


def _act(out, act: Optional[str]):
    if act is None:
        return out
    fn = getattr(F, act, None)
    if fn is None:
        raise ValueError(f"unsupported activation {act!r}")
    return fn(out)


def _param(shape, dtype="float32", attr=None, is_bias=False, init=None):
    return create_parameter(shape, dtype, attr=attr, is_bias=is_bias,
                            default_initializer=init)


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation: Optional[str] = None, name=None):
    """reference: static/nn/common.py:28 — flatten trailing dims, xW+b."""
    x = ensure_tensor(x)
    if num_flatten_dims < 0:
        num_flatten_dims = x.ndim + num_flatten_dims
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _param([in_dim, size], x.dtype, attr=weight_attr)
    b = None if bias_attr is False else _param([size], x.dtype,
                                               attr=bias_attr, is_bias=True)
    nfd = num_flatten_dims
    # leading dims read from the runtime value: the Executor feeds
    # shape-polymorphic batches (static.data None dims)
    flat = apply_op(lambda v: jnp.reshape(v, (*v.shape[:nfd], in_dim)), [x],
                    name="fc_flatten")
    return _act(F.linear(flat, w, b), activation)


def embedding(input, size: Sequence[int], is_sparse: bool = False,
              is_distributed: bool = False, padding_idx: Optional[int] = None,
              param_attr=None, dtype="float32"):
    """reference: static/nn/common.py embedding — creates the table."""
    w = _param(list(size), dtype, attr=param_attr,
               init=Normal(0.0, 1.0 / float(size[1]) ** 0.5))
    return F.embedding(ensure_tensor(input), w, padding_idx=padding_idx)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """reference: static/nn/common.py sparse_embedding (PS sparse table).
    TPU build: the table is a dense device array — XLA gathers ARE the
    sparse lookup; PS-side sparse storage lives in native/src/ps_table.cc."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def batch_norm(input, act=None, is_test: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, param_attr=None, bias_attr=None,
               data_layout: str = "NCHW", in_place: bool = False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var: bool = True,
               use_global_stats: bool = False):
    """reference: static/nn/common.py:1471."""
    x = ensure_tensor(input)
    c_axis = 1 if data_layout.startswith("NC") else x.ndim - 1
    C = x.shape[c_axis]
    scale = _param([C], x.dtype, attr=param_attr, init=Constant(1.0))
    bias = _param([C], x.dtype, attr=bias_attr, is_bias=True)
    mean = Tensor(jnp.zeros((C,), x.dtype))
    var = Tensor(jnp.ones((C,), x.dtype))
    out = F.batch_norm(x, mean, var, weight=scale, bias=bias,
                       training=not is_test and not use_global_stats,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    return _act(out, act)


def instance_norm(input, epsilon: float = 1e-5, param_attr=None,
                  bias_attr=None, name=None):
    """reference: static/nn/common.py instance_norm."""
    x = ensure_tensor(input)
    C = x.shape[1]
    scale = None if param_attr is False else _param([C], x.dtype,
                                                    attr=param_attr,
                                                    init=Constant(1.0))
    bias = None if bias_attr is False else _param([C], x.dtype,
                                                  attr=bias_attr,
                                                  is_bias=True)
    return F.instance_norm(x, weight=scale, bias=bias, eps=epsilon)


def data_norm(input, act=None, epsilon: float = 1e-5, param_attr=None,
              enable_scale_and_shift: bool = False, name=None,
              data_layout: str = "NCHW", do_model_average_for_mean_and_var=True,
              slot_dim: int = -1, sync_stats: bool = False,
              summary_decay_rate: float = 0.9999999):
    """reference: static/nn/common.py data_norm — normalization by learned
    batch summaries (batch_size / batch_sum / batch_square_sum), the CTR
    pipeline's streaming alternative to batch_norm."""
    x = ensure_tensor(input)
    D = x.shape[-1]
    batch_size = _param([D], x.dtype, init=Constant(1e4))
    batch_sum = _param([D], x.dtype, init=Constant(0.0))
    batch_sq = _param([D], x.dtype, init=Constant(1e4))

    def norm(v, n, s, sq):
        mean = s / n
        scale = jnp.sqrt(n / jnp.maximum(sq - s * mean, epsilon))
        return (v - mean) * scale

    out = apply_op(norm, [x, batch_size, batch_sum, batch_sq],
                   name="data_norm")
    if enable_scale_and_shift:
        scale = _param([D], x.dtype, attr=param_attr, init=Constant(1.0))
        shift = _param([D], x.dtype, attr=param_attr, is_bias=True)
        out = out * scale + shift
    return _act(out, act)


def group_norm(input, groups: int, epsilon: float = 1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout: str = "NCHW",
               name=None):
    """reference: static/nn/common.py group_norm."""
    x = ensure_tensor(input)
    c_axis = 1 if data_layout.startswith("NC") else x.ndim - 1
    C = x.shape[c_axis]
    scale = None if param_attr is False else _param([C], x.dtype,
                                                    attr=param_attr,
                                                    init=Constant(1.0))
    bias = None if bias_attr is False else _param([C], x.dtype,
                                                  attr=bias_attr,
                                                  is_bias=True)
    out = F.group_norm(x, groups, epsilon=epsilon, weight=scale, bias=bias,
                       data_format=data_layout)
    return _act(out, act)


def layer_norm(input, scale: bool = True, shift: bool = True,
               begin_norm_axis: int = 1, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    """reference: static/nn/common.py layer_norm — normalizes over dims
    [begin_norm_axis:]."""
    x = ensure_tensor(input)
    norm_shape = x.shape[begin_norm_axis:]
    w = _param(norm_shape, x.dtype, attr=param_attr,
               init=Constant(1.0)) if scale else None
    b = _param(norm_shape, x.dtype, attr=bias_attr,
               is_bias=True) if shift else None
    return _act(F.layer_norm(x, norm_shape, weight=w, bias=b,
                             epsilon=epsilon), act)


def _conv_nd(ndim, fname):
    default_df = "NCHW" if ndim == 2 else "NCDHW"

    def conv(input, num_filters: int, filter_size, stride=1, padding=0,
             dilation=1, groups=None, param_attr=None, bias_attr=None,
             use_cudnn: bool = True, act=None, name=None,
             data_format: str = None):
        data_format = data_format or default_df
        x = ensure_tensor(input)
        groups = groups or 1
        c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        C = x.shape[c_axis]
        ks = [filter_size] * ndim if isinstance(filter_size, int) \
            else list(filter_size)
        fan_in = C // groups * int(np.prod(ks))
        w = _param([num_filters, C // groups, *ks], x.dtype, attr=param_attr,
                   init=Normal(0.0, (2.0 / fan_in) ** 0.5))
        b = None if bias_attr is False else _param([num_filters], x.dtype,
                                                   attr=bias_attr,
                                                   is_bias=True)
        out = getattr(F, fname)(x, w, bias=b, stride=stride, padding=padding,
                                dilation=dilation, groups=groups,
                                data_format=data_format)
        return _act(out, act)
    conv.__name__ = fname
    return conv


conv2d = _conv_nd(2, "conv2d")
conv3d = _conv_nd(3, "conv3d")


def _conv_transpose_nd(ndim, fname):
    default_df = "NCHW" if ndim == 2 else "NCDHW"

    def convt(input, num_filters: int, output_size=None, filter_size=None,
              padding=0, stride=1, dilation=1, groups=None, param_attr=None,
              bias_attr=None, use_cudnn: bool = True, act=None, name=None,
              data_format: str = None):
        data_format = data_format or default_df
        x = ensure_tensor(input)
        groups = groups or 1
        if filter_size is None:
            raise ValueError(f"{fname}: filter_size is required (output_size"
                             "-derived filter inference is not supported)")
        c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        C = x.shape[c_axis]
        ks = [filter_size] * ndim if isinstance(filter_size, int) \
            else list(filter_size)
        w = _param([C, num_filters // groups, *ks], x.dtype, attr=param_attr,
                   init=XavierNormal())
        b = None if bias_attr is False else _param([num_filters], x.dtype,
                                                   attr=bias_attr,
                                                   is_bias=True)
        out = getattr(F, fname)(x, w, bias=b, stride=stride, padding=padding,
                                groups=groups, dilation=dilation,
                                output_size=output_size,
                                data_format=data_format)
        return _act(out, act)
    convt.__name__ = fname
    return convt


conv2d_transpose = _conv_transpose_nd(2, "conv2d_transpose")
conv3d_transpose = _conv_transpose_nd(3, "conv3d_transpose")


def deform_conv2d(input, offset, mask, num_filters: int, filter_size,
                  stride=1, padding=0, dilation=1, groups=None,
                  deformable_groups=None, im2col_step=None, param_attr=None,
                  bias_attr=None, name=None):
    """reference: static/nn/common.py deform_conv2d (v2 when mask given)."""
    from ...vision.ops import deform_conv2d as _dcn

    x = ensure_tensor(input)
    groups = groups or 1
    ks = [filter_size] * 2 if isinstance(filter_size, int) \
        else list(filter_size)
    C = x.shape[1]
    w = _param([num_filters, C // groups, *ks], x.dtype, attr=param_attr,
               init=XavierNormal())
    b = None if bias_attr is False else _param([num_filters], x.dtype,
                                               attr=bias_attr, is_bias=True)
    return _dcn(x, offset, w, mask=mask, bias=b, stride=stride,
                padding=padding, dilation=dilation,
                deformable_groups=deformable_groups or 1, groups=groups)


def bilinear_tensor_product(x, y, size: int, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: static/nn/common.py bilinear_tensor_product —
    out_k = x W_k yᵀ + b."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    dx, dy = x.shape[-1], y.shape[-1]
    w = _param([size, dx, dy], x.dtype, attr=param_attr, init=XavierNormal())
    b = None if bias_attr is False else _param([size], x.dtype,
                                               attr=bias_attr, is_bias=True)
    ins = [x, y, w] + ([b] if b is not None else [])

    def btp(xv, yv, wv, *rest):
        out = jnp.einsum("bi,kij,bj->bk", xv, wv, yv)
        return out + rest[0] if rest else out

    return _act(apply_op(btp, ins, name="bilinear_tensor_product"), act)


def row_conv(input, future_context_size: int, param_attr=None, act=None):
    """reference: static/nn/common.py row_conv — lookahead convolution:
    out[t] = Σ_{i=0..k} x[t+i] ⊙ w[i] (zero past the end)."""
    x = ensure_tensor(input)
    D = x.shape[-1]
    k = int(future_context_size)
    w = _param([k + 1, D], x.dtype, attr=param_attr, init=Constant(0.0))

    def rc(v, wv):
        T = v.shape[-2]
        pad = [(0, 0)] * v.ndim
        pad[-2] = (0, k)
        vp = jnp.pad(v, pad)
        out = sum(jnp.take(vp, jnp.arange(i, T + i), axis=-2) * wv[i]
                  for i in range(k + 1))
        return out

    return _act(apply_op(rc, [x, w], name="row_conv"), act)


def spectral_norm(weight, dim: int = 0, power_iters: int = 1,
                  eps: float = 1e-12, name=None):
    """reference: static/nn/common.py:2158 — W / σ(W) by power iteration."""
    w = ensure_tensor(weight)
    h = w.shape[dim]

    def sn(wv):
        mat = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
        u = jnp.ones((h,), wv.dtype) / jnp.sqrt(jnp.asarray(h, wv.dtype))
        v = None
        for _ in range(max(1, power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return wv / sigma

    return apply_op(sn, [w], name="spectral_norm")


def prelu(x, mode: str, param_attr=None, data_format: str = "NCHW",
          name=None):
    """reference: static/nn/common.py prelu — modes all/channel/element."""
    x = ensure_tensor(x)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        shape = [x.shape[c_axis]]
    elif mode == "element":
        shape = list(x.shape[1:])
    else:
        raise ValueError(f"prelu: bad mode {mode!r}")
    alpha = _param(shape, x.dtype, attr=param_attr, init=Constant(0.25))

    def pr(v, a):
        if mode == "channel" and data_format.startswith("NC"):
            a = a.reshape((1, -1) + (1,) * (v.ndim - 2))
        return jnp.where(v >= 0, v, v * a)

    return apply_op(pr, [x, alpha], name="prelu")


def continuous_value_model(input, cvm, use_cvm: bool = True):
    """reference: static/nn/common.py continuous_value_model — CTR cvm op:
    keep (use_cvm) or strip the leading show/click columns."""
    x = ensure_tensor(input)
    if use_cvm:
        return apply_op(lambda v: v, [x], name="cvm")
    return apply_op(lambda v: v[:, 2:], [x], name="cvm")


def nce(input, label, num_total_classes: int, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples: Optional[int] = None,
        name=None, sampler: str = "uniform", custom_dist=None, seed: int = 0,
        is_sparse: bool = False):
    """reference: static/nn/loss.py nce — noise-contrastive estimation.

    TPU build: negatives are drawn host-side once at build time (static
    sample set, uniform/log_uniform), the loss is the standard binary
    NCE objective -log σ(s⁺) - Σ log σ(-s⁻), batched on the MXU."""
    x = ensure_tensor(input)
    lbl = ensure_tensor(label)
    D = x.shape[-1]
    n_neg = int(num_neg_samples or 10)
    w = _param([num_total_classes, D], x.dtype, attr=param_attr,
               init=Normal(0.0, 1.0 / D ** 0.5))
    b = None if bias_attr is False else _param([num_total_classes], x.dtype,
                                               attr=bias_attr, is_bias=True)
    rng = np.random.RandomState(seed or 1)
    if sampler == "log_uniform":
        p = 1.0 / np.arange(1, num_total_classes + 1)
        p /= p.sum()
        neg = rng.choice(num_total_classes, size=(n_neg,), p=p)
    elif sampler == "custom_dist" and custom_dist is not None:
        p = np.asarray(custom_dist, dtype=np.float64)
        neg = rng.choice(num_total_classes, size=(n_neg,), p=p / p.sum())
    else:
        neg = rng.randint(0, num_total_classes, size=(n_neg,))
    neg = jnp.asarray(neg, jnp.int32)

    ins = [x, lbl, w] + ([b] if b is not None else [])

    def nce_loss(xv, lv, wv, *rest):
        bv = rest[0] if rest else jnp.zeros((num_total_classes,), xv.dtype)
        lv = lv.reshape(-1).astype(jnp.int32)
        pos_s = jnp.sum(xv * wv[lv], axis=-1) + bv[lv]
        neg_s = xv @ wv[neg].T + bv[neg]
        loss = jax.nn.softplus(-pos_s) + \
            jnp.sum(jax.nn.softplus(neg_s), axis=-1)
        return loss[:, None]

    return apply_op(nce_loss, ins, name="nce")
