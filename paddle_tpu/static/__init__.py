"""paddle.static facade: Program / Executor / data / program_guard.

Reference parity: python/paddle/static/ — ``Program`` (fluid/framework.py
:5222), ``Executor`` (fluid/executor.py:893 → C++ StandaloneExecutor/
InterpreterCore), ``data`` (static/input.py), ``program_guard``,
``save/load_inference_model`` (static/io.py), plus ``InputSpec`` and the
``nn`` sublayer helpers.

TPU-native collapse (SURVEY.md §7 step 5): the reference's Program is an
op-desc graph executed instruction-by-instruction by InterpreterCore. Here
the eager tape IS the graph — ``static.data`` creates placeholder leaves,
the user's layer calls record tape nodes as usual, and ``Executor.run``
replays the recorded subgraph placeholders→fetches as ONE pure jax
function compiled per feed signature (the whole InterpreterCore scheduling
problem collapses into XLA's static schedule). ``Optimizer.minimize``
inside a program records the loss + optimizer so ``run`` performs the
fused train step (grads via jax, update via the optimizer machinery).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..jit.static_function import InputSpec  # noqa: F401 (re-export)
from ..ops._apply import ensure_tensor
from ..tensor import Parameter, Tensor
from .. import dtypes as _dtypes

from .legacy import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy,
    ExponentialMovingAverage, Print, Variable, WeightNormParamAttr,
    accuracy, append_backward, auc, create_global_var, create_parameter,
    ctr_metric_bundle, deserialize_persistables, deserialize_program,
    device_guard, exponential_decay, gradients, load, load_from_file,
    load_program_state, name_scope, normalize_program, py_func, save,
    save_to_file, scope_guard, serialize_persistables, serialize_program,
    set_program_state,
)

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "InputSpec",
    "save_inference_model", "load_inference_model", "cpu_places",
    "cuda_places", "xpu_places", "global_scope",
    "append_backward", "gradients", "scope_guard", "name_scope",
    "BuildStrategy", "ExecutionStrategy", "CompiledProgram", "Print",
    "py_func", "WeightNormParamAttr", "ExponentialMovingAverage",
    "save", "load", "save_to_file", "load_from_file",
    "serialize_program", "serialize_persistables", "deserialize_program",
    "deserialize_persistables", "set_program_state", "normalize_program",
    "Variable", "create_global_var", "create_parameter", "device_guard",
    "load_program_state", "accuracy", "auc", "exponential_decay",
    "ctr_metric_bundle",
]


class Program:
    """reference: fluid/framework.py:5222 — here: a registry of placeholder
    inputs + (after minimize) the training objective."""

    def __init__(self):
        self.placeholders: Dict[str, Tensor] = {}
        self.declared_shapes: Dict[str, tuple] = {}  # None dims preserved
        self.loss: Optional[Tensor] = None
        self.optimizer = None
        self.random_seed = 0

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.placeholders = dict(self.placeholders)
        p.declared_shapes = dict(self.declared_shapes)
        if not for_test:
            p.loss, p.optimizer = self.loss, self.optimizer
        return p

    def global_block(self):
        return self

    @property
    def var_names(self):
        return list(self.placeholders)

    # -- parameter snapshot (static/legacy.py save/load & serialization) ----
    def _program_parameters(self) -> list:
        """Parameters reachable from the declared objective."""
        if self.optimizer is not None and \
                getattr(self.optimizer, "_parameter_list", None):
            return list(self.optimizer._parameter_list)
        if self.loss is not None:
            return _collect_parameters(self.loss)
        return []

    def _param_key(self, i: int, p) -> str:
        name = getattr(p, "name", None)
        return name if name else f"param_{i}"

    def _param_state(self) -> dict:
        import numpy as _np

        return {self._param_key(i, p): _np.asarray(p._value)
                for i, p in enumerate(self._program_parameters())}

    def _set_param_state(self, state: dict) -> None:
        import jax.numpy as _jnp

        params = self._program_parameters()
        used = set()
        for i, p in enumerate(params):
            for key in (self._param_key(i, p), f"param_{i}"):
                if key in state:
                    p._set_value(_jnp.asarray(state[key], p._value.dtype))
                    used.add(key)
                    break
        unused = set(state) - used
        if unused:
            # reference set_program_state errors on unused keys — silent
            # partial loads are how wrong checkpoints sneak into evals
            raise ValueError(
                f"state dict keys not matched to any program parameter: "
                f"{sorted(unused)[:8]}{'...' if len(unused) > 8 else ''}")

    def _placeholder_spec(self) -> dict:
        return {name: {"shape": list(self.declared_shapes.get(
                           name, tuple(t.shape))),
                       "dtype": str(t.dtype)}
                for name, t in self.placeholders.items()}


_default_main = Program()
_default_startup = Program()
_guard_stack: List[tuple] = []
_declared_by_uid: Dict[int, tuple] = {}  # placeholder uid -> declared shape


def default_main_program() -> Program:
    """reference: fluid/framework.py default_main_program."""
    return _guard_stack[-1][0] if _guard_stack else _default_main


def default_startup_program() -> Program:
    return _guard_stack[-1][1] if _guard_stack else _default_startup


class program_guard:
    """reference: static/program_guard — scope main/startup programs."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program or Program()

    def __enter__(self):
        _guard_stack.append((self.main, self.startup))
        return self

    def __exit__(self, *exc):
        _guard_stack.pop()
        return False


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0) -> Tensor:
    """reference: static/input.py data — a placeholder leaf registered with
    the current program. ``None``/-1 dims become 1 at build; Executor.run
    recompiles per concrete feed shape (polymorphic like the reference)."""
    concrete = [1 if (d is None or int(d) < 0) else int(d) for d in shape]
    dt = _dtypes.convert_dtype(dtype)
    # stop_gradient=False: every downstream op must record a tape node even
    # when no Parameter participates, or Executor.run's replay would hand
    # back stale build-time values for parameter-free fetches
    t = Tensor(jnp.zeros(concrete, dt), stop_gradient=False)
    t.name = name
    prog = default_main_program()
    prog.placeholders[name] = t
    # declared shape (None dims preserved) — save_inference_model exports
    # polymorphic dims from this, not the concretized build shape. Keyed by
    # uid in a module registry too: at save time the declaring program may
    # no longer be the guarded default.
    declared = tuple(
        None if (d is None or int(d) < 0) else int(d) for d in shape)
    prog.declared_shapes[name] = declared
    _declared_by_uid[t._uid] = declared
    return t


def _collect_parameters_multi(fetches,
                              trainable_only: bool = True) -> List[Parameter]:
    seen, out = set(), []
    for f in fetches:
        for p in _collect_parameters(f, trainable_only=trainable_only):
            if id(p) not in seen:
                seen.add(id(p))
                out.append(p)
    return out


def _collect_parameters(loss: Tensor,
                        trainable_only: bool = True) -> List[Parameter]:
    """All trainable Parameter leaves reachable from ``loss``'s tape — the
    static-graph minimize() contract (reference: minimize collects every
    trainable var in the program when no parameter list is given)."""
    seen_nodes, seen_ids, out = set(), set(), []
    stack = [loss._grad_node] if loss._grad_node is not None else []
    while stack:
        node = stack.pop()
        if id(node) in seen_nodes:
            continue
        seen_nodes.add(id(node))
        for t, uid, producer in node.edges:
            if producer is not None:
                stack.append(producer)
            elif (isinstance(t, Parameter)
                  and (not t.stop_gradient or not trainable_only)
                  and t._uid == uid and id(t) not in seen_ids):
                seen_ids.add(id(t))
                out.append(t)
    return out


class Executor:
    """reference: fluid/executor.py:893. ``run`` compiles the recorded
    subgraph per (program, feed shapes) and executes it as one XLA call."""

    def __init__(self, place=None):
        self.place = place
        self._cache: dict = {}

    @staticmethod
    def _reachable_uids(fetches) -> set:
        """uids of every tensor the fetch subgraph reads."""
        seen_nodes, uids = set(), set()
        stack = [t._grad_node for t in fetches if t._grad_node is not None]
        uids.update(t._uid for t in fetches)
        while stack:
            node = stack.pop()
            if id(node) in seen_nodes:
                continue
            seen_nodes.add(id(node))
            for t, uid, producer in node.edges:
                uids.add(uid)
                if producer is not None:
                    stack.append(producer)
        return uids

    def _resolve_fetch(self, program: Program, f):
        if isinstance(f, Tensor):
            return f
        if isinstance(f, str):
            if f in program.placeholders:
                return program.placeholders[f]
            raise ValueError(
                f"fetch_list name {f!r} is not a program placeholder; pass "
                "the Tensor object for intermediate variables (the tape has "
                "no global name registry)")
        raise TypeError(f"bad fetch_list entry: {f!r}")

    def run(self, program: Optional[Program] = None, feed: dict = None,
            fetch_list: Sequence = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = [self._resolve_fetch(program, f)
                      for f in (fetch_list or [])]
        if not fetch_list and program.loss is None:
            return []  # startup programs: parameters already initialized

        from ..incubate.autograd import _replay_function

        train = program.loss is not None and program.optimizer is not None
        fetches = list(fetch_list)
        loss_idx = None
        if train:
            for i, f in enumerate(fetches):
                if f is program.loss:
                    loss_idx = i
                    break
            if loss_idx is None:
                fetches.append(program.loss)
                loss_idx = len(fetches) - 1

        # every placeholder the fetch subgraph reads MUST be fed — a missing
        # feed silently evaluating to build-time zeros is how wrong numbers
        # (and wrong gradients) escape unnoticed
        needed = self._reachable_uids(fetches)
        missing = [n for n, t in program.placeholders.items()
                   if t._uid in needed and n not in feed]
        if missing:
            raise KeyError(
                f"feed is missing required placeholder(s): {missing}")

        # sort names so feed-dict insertion order cannot desync the cached
        # function's positional binding
        ph_names = sorted(n for n in feed if n in program.placeholders)
        placeholders = [program.placeholders[n] for n in ph_names]
        # parameters are jit ARGUMENTS in eval mode too: baking them in as
        # constants would freeze eval results at first-run weights
        # eval path lifts EVERY reachable Parameter (frozen ones included)
        # to jit arguments — constants baked into the cache would freeze
        # later weight updates out of eval results
        params = list(program.optimizer._parameter_list or []) if train \
            else _collect_parameters_multi(fetches, trainable_only=False)

        # bind feeds (shape-polymorphic: replace placeholder values)
        for n, t in zip(ph_names, placeholders):
            t._value = ensure_tensor(np.asarray(feed[n]))._value

        key = (id(program), tuple(t._uid for t in fetches), train,
               tuple(ph_names),
               tuple((tuple(t._value.shape), str(t._value.dtype))
                     for t in placeholders))
        cached = self._cache.get(key)
        if cached is None:
            fn, _ = _replay_function(fetches, placeholders + params)
            n_ph = len(placeholders)

            if train and params:
                def loss_and_outs(*vals):
                    outs = fn(*vals)
                    outs = outs if isinstance(outs, tuple) else (outs,)
                    return jnp.reshape(outs[loss_idx], ()), outs

                def step_fn(*vals):
                    # one forward trace: grads + every fetch via has_aux
                    grads, outs = jax.grad(
                        lambda *pv: loss_and_outs(
                            *(list(vals[:n_ph]) + list(pv))),
                        argnums=tuple(range(len(vals) - n_ph)),
                        has_aux=True)(*vals[n_ph:])
                    if not isinstance(grads, (tuple, list)):
                        grads = (grads,)
                    return outs, tuple(grads)

                cached = jax.jit(step_fn)
            else:
                def fwd_fn(*vals):
                    outs = fn(*vals)
                    return outs if isinstance(outs, tuple) else (outs,)

                cached = jax.jit(fwd_fn)
            self._cache[key] = cached

        in_vals = [t._value for t in placeholders] \
            + [p._value for p in params]
        if train and params:
            outs, grads = cached(*in_vals)
            for p, g in zip(params, grads):
                p.grad = Tensor(g) if p.grad is None \
                    else Tensor(p.grad._value + g)
            program.optimizer.step()
            program.optimizer.clear_grad()
        else:
            outs = cached(*in_vals)
            if train:
                program.optimizer.step()
                program.optimizer.clear_grad()
        outs = outs[: len(fetch_list)] if fetch_list else outs
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def close(self):
        pass


# ------------------------------------------------------------ inference io
def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None, **kwargs):
    """reference: static/io.py save_inference_model — exports the
    placeholders→fetches subgraph via the jit StableHLO path."""
    from .. import jit
    from ..nn.layer_base import Layer

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    from ..incubate.autograd import _replay_function

    fn, in_vals = _replay_function(list(fetch_vars), list(feed_vars))

    class _Prog(Layer):
        def forward(self, *xs):
            out = fn(*[x._value if isinstance(x, Tensor) else x for x in xs])
            if isinstance(out, tuple):
                return tuple(Tensor(o) for o in out)
            return Tensor(out)

    specs = [InputSpec(_declared_by_uid.get(v._uid, tuple(v.shape)),
                       str(v._value.dtype)) for v in feed_vars]
    jit.save(_Prog(), path_prefix, input_spec=specs)


def load_inference_model(path_prefix: str, executor, **kwargs):
    """reference: static/io.py load_inference_model — returns
    (program-like callable, feed_names, fetch_names)."""
    from .. import jit

    layer = jit.load(path_prefix)
    return layer, getattr(layer, "_feed_names", None), \
        getattr(layer, "_fetch_names", None)


# ---------------------------------------------------------------- place API
def cpu_places(device_count: Optional[int] = None):
    return ["cpu"] * (device_count or 1)


def cuda_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


_scope = {}


def global_scope():
    return _scope


# imported last: static.nn's layers build on the facade above
from . import nn  # noqa: F401,E402

__all__.append("nn")
