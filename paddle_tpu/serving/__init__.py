"""paddle_tpu.serving — continuous-batching LLM inference engine.

The production decode path the ROADMAP's "millions of users" north star
needs and ``GenerationMixin.generate`` (one static batch, dense caches)
cannot provide: paged KV memory with refcounted copy-on-write sharing
and a radix prefix cache — shared prompt prefixes admit without
re-prefilling (kv_cache.py, docs/SERVING.md "Prefix caching") — FCFS
token-budget admission charging only each request's uncovered suffix
(scheduler.py), a single compiled ragged-paged-attention decode
step over fixed batch slots (engine.py + ops/pallas/paged_attention.py),
an OpenAI-ish front door with streaming (api.py), and a fleet-scale
control plane (router.py): least-loaded dispatch, health-gated
auto-drain/failover with exactly-once requeue, rolling weight reload
from committed checkpoints, and multi-model tenancy. Always-on
telemetry — TTFT / inter-token-latency / queue-wait histograms,
lifecycle counters, page-pool gauges — lands in ``paddle_tpu.metrics``
(docs/OBSERVABILITY.md). The resilience layer (docs/RESILIENCE.md) rides
``paddle_tpu.faults``: per-request deadlines and ``cancel()``, a bounded
queue that rejects with a ``retry_after_s`` hint (BackpressureError),
NaN-logit quarantine that never poisons batch-mates, isolated stream
callbacks, and a step watchdog surfaced through ``/healthz``. Durability
is opt-in (wal.py): ``Router(wal_dir=...)`` journals every admission and
committed token batch to a CRC-framed write-ahead log under ONE
group-commit fsync per step, and ``Router.recover()`` replays it after a
process death — unfinished requests re-admit through the journaled
re-prefill path and streams complete bit-identical with exactly-once
chunk delivery (docs/RESILIENCE.md "Durability").
Multi-tenancy rides the ONE compiled step as data: batched multi-LoRA
adapters (adapters.py — hot-loaded fleet-wide with zero recompiles,
routed by ``(model_id, adapter_id)``) and token-level constrained
decoding (grammar.py — JSON-schema/regex compiled to a DFA whose
allow-masks gate sampling in-step, migration-safe via FSM journals).

Quick start (docs/SERVING.md has the sizing math; examples/serve_llama.py
is runnable):

    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import ServingEngine

    engine = ServingEngine(LlamaForCausalLM(llama_tiny()), page_size=16,
                           max_batch_slots=8)
    engine.add_request(prompt_ids, max_new_tokens=64, eos_token_id=2)
    outputs = engine.run()          # continuous batching until drained
"""
from .adapters import AdapterStore, random_adapter
from .api import CompletionAPI
from .engine import ServingEngine
from .grammar import GrammarFSM, ToyTokenizer, schema_to_regex, toy_tokenizer
from .kv_cache import (HostPageStore, PagedKVCachePool, PrefixCache,
                       normalize_kv_dtype, page_bytes, pages_for_hbm_budget)
from .overload import (AdmissionShedError, DrainEstimator, OverloadConfig,
                       OverloadController, RetryBudget)
from .router import EngineHandle, NoHealthyEngineError, Router
from .scheduler import (BackpressureError, FCFSScheduler, Request,
                        RequestOutput)
from .spec import NGramDrafter
from .tracing import (TTFT_BUCKETS, RequestTracer, attribute_ttft,
                      get_tracer, set_tracer, validate_events)
from .wal import RequestWAL, WalRequest, WalState

__all__ = [
    "ServingEngine", "PagedKVCachePool", "PrefixCache", "FCFSScheduler",
    "Request", "RequestOutput", "CompletionAPI",
    "BackpressureError", "Router", "EngineHandle", "NoHealthyEngineError",
    "NGramDrafter", "page_bytes", "pages_for_hbm_budget",
    "HostPageStore", "normalize_kv_dtype",
    "AdapterStore", "random_adapter", "GrammarFSM", "ToyTokenizer",
    "toy_tokenizer", "schema_to_regex",
    "RequestTracer", "TTFT_BUCKETS", "attribute_ttft", "get_tracer",
    "set_tracer", "validate_events",
    "OverloadController", "OverloadConfig", "DrainEstimator",
    "AdmissionShedError", "RetryBudget",
    "RequestWAL", "WalRequest", "WalState",
]
