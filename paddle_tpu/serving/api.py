"""OpenAI-ish completion front door over the serving engine.

The request/response half of the serving stack: an in-process API whose
payload shapes mirror the OpenAI completions surface (``id`` /
``object: "text_completion"`` / ``choices[].finish_reason`` / ``usage``)
so an HTTP shim is a ~20-line adapter, plus per-request streaming
callbacks (the SSE chunk analogue). Pooling follows the
``inference.PredictorPool`` idiom (inference/__init__.py — ``retrieve(i)``
hands a caller-thread its own slot): one model's weights are shared (jax
arrays are immutable) while each pool slot owns an independent engine —
queue, pages, and compiled-step state are per-slot, handles must not be
shared across threads.

Token ids in, token ids out: tokenization is the caller's concern (pass
``detokenize=`` to get ``text`` filled in the response).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from .. import faults, metrics
from .engine import ServingEngine
from .scheduler import BackpressureError

__all__ = ["CompletionAPI", "EnginePool"]

_cmpl_counter = itertools.count()


class CompletionAPI:
    """OpenAI-completions-shaped facade over one :class:`ServingEngine`."""

    def __init__(self, engine: ServingEngine, model_name: str = "paddle-tpu",
                 detokenize: Optional[Callable[[Sequence[int]], str]] = None):
        self.engine = engine
        self.model_name = model_name
        self.detokenize = detokenize
        reg = metrics.get_registry()
        self._m_completions = reg.counter(
            "paddle_tpu_serving_completions_total",
            "create_completion calls by outcome", labels=("status",))
        self._m_latency = reg.histogram(
            "paddle_tpu_serving_completion_seconds",
            "Whole create_completion latency: queue + prefill + decode "
            "to the last choice finishing")

    def create_completion(self, prompt, max_tokens: int = 16,
                          temperature: float = 0.0,
                          stop_token_id: Optional[int] = None,
                          seed: int = 0, echo: bool = False,
                          stream_cb: Optional[Callable] = None,
                          deadline_s: Optional[float] = None) -> dict:
        """Run one or more prompts to completion and return an OpenAI-ish
        response dict. ``prompt`` is a token-id list or a batch of them
        (one ``choices`` entry each, continuous-batched through the
        engine). ``stream_cb(chunk)`` receives OpenAI-chunk-shaped dicts
        as tokens land. Each batch-mate's first token samples from its
        own stream (``seed + index``), so n-best sampling of one prompt
        diverges instead of returning n identical choices. ``deadline_s``
        bounds each choice from enqueue; an expired one comes back with
        ``finish_reason="timeout"`` and whatever tokens it produced."""
        t0 = time.perf_counter()
        prompts = self._as_batch(prompt)
        # validate the WHOLE batch before queueing anything: a rejected
        # later prompt must not strand already-queued batch-mates
        try:
            for p in prompts:
                self.engine.check_request(p.size, max_tokens)
        except ValueError:
            self._m_completions.labels(status="rejected").inc()
            raise
        cid = f"cmpl-{next(_cmpl_counter)}"
        req_ids = []
        try:
            for idx, p in enumerate(prompts):
                cb = None
                if stream_cb is not None:
                    cb = self._chunk_cb(stream_cb, cid, idx)
                req_ids.append(self.engine.add_request(
                    p, max_new_tokens=max_tokens, temperature=temperature,
                    eos_token_id=stop_token_id, seed=seed + idx,
                    stream_cb=cb, deadline_s=deadline_s))
        except Exception:
            # enqueue failed mid-batch (bounded queue filled, or a
            # Request invariant check_request can't see, e.g. an empty
            # prompt): silently un-queue the mates already added — from
            # the caller's perspective this call was never accepted, so
            # no cancelled counters, no terminal stream chunks, no
            # orphans running under the next create_completion
            for rid in req_ids:
                self.engine.scheduler.remove(rid)
            self._m_completions.labels(status="rejected").inc()
            raise
        outputs = self.engine.run()
        choices = []
        usage_p = usage_c = 0
        for idx, rid in enumerate(req_ids):
            out = outputs[rid]
            ids = list(out.token_ids)
            full = (list(map(int, out.prompt_token_ids)) + ids
                    if echo else ids)
            choices.append({
                "index": idx,
                "token_ids": full,
                "text": (self.detokenize(full)
                         if self.detokenize is not None else None),
                # pass the engine's reason straight through — the
                # resilience reasons ("timeout"/"cancelled"/"nan"/
                # "error", docs/SERVING.md table) must not be masked
                # as a normal "length" stop
                "finish_reason": out.finish_reason,
            })
            usage_p += int(out.prompt_token_ids.size)
            usage_c += out.n_gen
        self._m_completions.labels(status="ok").inc()
        self._m_latency.observe(time.perf_counter() - t0)
        return {
            "id": cid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": choices,
            "usage": {"prompt_tokens": usage_p,
                      "completion_tokens": usage_c,
                      "total_tokens": usage_p + usage_c},
        }

    def _chunk_cb(self, stream_cb, cid, idx):
        def cb(req_id, token, finished):
            # the engine's terminal callback passes the finish reason
            # (docs/SERVING.md table) as `finished`, so streamed chunks
            # agree with the final response's choices[].finish_reason
            try:
                stream_cb({
                    "id": cid,
                    "object": "text_completion.chunk",
                    "model": self.model_name,
                    "choices": [{
                        "index": idx,
                        "token_id": None if token is None else int(token),
                        "finish_reason": finished or None,
                    }],
                })
            except Exception as e:
                # a raising USER callback must never abort the engine
                # step its batch-mates are riding: normalize to
                # CallbackError (original chained) — the engine's
                # callback isolation records it and retires THIS request
                # with finish_reason="error"
                raise faults.CallbackError(
                    f"stream_cb raised for {cid} choice {idx}") from e

        return cb

    @staticmethod
    def _as_batch(prompt) -> List[np.ndarray]:
        if isinstance(prompt, (list, tuple)):
            if not prompt:
                raise ValueError("empty prompt batch")
            if np.ndim(prompt[0]) == 0:  # flat token-id list
                return [np.asarray(prompt, np.int32)]
            # ragged batch: one choices entry per prompt
            return [np.asarray(p, np.int32).reshape(-1) for p in prompt]
        arr = np.asarray(prompt)
        if arr.ndim == 1:
            return [arr.astype(np.int32)]
        if arr.ndim == 2:
            return [row.astype(np.int32) for row in arr]
        raise ValueError(f"prompt rank {arr.ndim} unsupported")


class EnginePool:
    """Pool of engines over ONE model for multi-threaded serving —
    the ``inference.PredictorPool`` idiom: ``retrieve(i)`` hands thread i
    its own engine (private queue/pages/compiled-step cache); the model
    weights are shared process-wide."""

    def __init__(self, model, size: int = 1, **engine_kwargs):
        self._engines = [ServingEngine(model, **engine_kwargs)
                         for _ in range(int(size))]
        self._rr = itertools.count()
        self._rr_lock = threading.Lock()

    def retrieve(self, idx: int) -> ServingEngine:
        if not 0 <= int(idx) < len(self._engines):
            raise IndexError(
                f"engine index {idx} out of range for EnginePool of size "
                f"{len(self._engines)} (valid: 0..{len(self._engines) - 1})")
        return self._engines[int(idx)]

    def next(self) -> ServingEngine:
        """Round-robin handout: the ROTATION is thread-safe, the engines
        are not — size the pool to at least the worker count so no two
        concurrent callers drive one engine (same contract as
        ``retrieve``: one engine per thread at a time). Used by
        examples/serve_llama.py."""
        with self._rr_lock:
            i = next(self._rr) % len(self._engines)
        return self._engines[i]

    def __len__(self) -> int:
        return len(self._engines)
