"""OpenAI-ish completion front door over the serving engine / router.

The request/response half of the serving stack: an in-process API whose
payload shapes mirror the OpenAI completions surface (``id`` /
``object: "text_completion"`` / ``choices[].finish_reason`` / ``usage``)
so an HTTP shim is a ~20-line adapter, plus per-request streaming
callbacks (the SSE chunk analogue).

``CompletionAPI`` fronts either ONE :class:`~.engine.ServingEngine`
(single-replica, as in PRs 1–3) or a :class:`~.router.Router` fleet: with
a router, ``create_completion(model=...)`` routes through least-loaded
dispatch and health gating, and the whole fleet is driven so a request
requeued off a draining engine still delivers here. Token ids in, token
ids out: tokenization is the caller's concern (pass ``detokenize=`` to
get ``text`` filled in the response). ``adapter_id=`` selects a LoRA
tenant (routed only to engines holding it); ``grammar=`` constrains
every choice to a compiled :class:`~.grammar.GrammarFSM`.
"""
from __future__ import annotations

import itertools
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from .. import faults, metrics
from .engine import ServingEngine
from .router import NoHealthyEngineError, Router

__all__ = ["CompletionAPI"]

_cmpl_counter = itertools.count()


class CompletionAPI:
    """OpenAI-completions-shaped facade over one :class:`ServingEngine`
    or a :class:`Router` fleet (pass either as ``backend``)."""

    def __init__(self, backend, model_name: str = "paddle-tpu",
                 detokenize: Optional[Callable[[Sequence[int]], str]] = None):
        if isinstance(backend, Router):
            self.router: Optional[Router] = backend
            self.engine: Optional[ServingEngine] = None
        else:
            self.router = None
            self.engine = backend
        self.model_name = model_name
        self.detokenize = detokenize
        reg = metrics.get_registry()
        self._m_completions = reg.counter(
            "paddle_tpu_serving_completions_total",
            "create_completion calls by outcome", labels=("status",))
        self._m_latency = reg.histogram(
            "paddle_tpu_serving_completion_seconds",
            "Whole create_completion latency: queue + prefill + decode "
            "to the last choice finishing")

    def _route(self, model: Optional[str],
               adapter_id: Optional[str] = None):
        """(engine, handle, response_model_name) for this completion."""
        if self.router is not None:
            # ValueError on unknown id; tenancy is (model, adapter) —
            # only engines holding the adapter are candidates
            handle = self.router.select(model, adapter_id=adapter_id)
            # echo the tenant the caller named; the display name covers
            # the single-model default (same as the engine-backed path)
            return handle.engine, handle, (model if model is not None
                                           else self.model_name)
        if model is not None and model != self.model_name:
            raise ValueError(
                f"unknown model id {model!r} (this CompletionAPI serves "
                f"only {self.model_name!r}); front a Router to serve "
                f"several models")
        return self.engine, None, self.model_name

    def create_completion(self, prompt, max_tokens: int = 16,
                          temperature: float = 0.0,
                          stop_token_id: Optional[int] = None,
                          seed: int = 0, echo: bool = False,
                          stream_cb: Optional[Callable] = None,
                          deadline_s: Optional[float] = None,
                          model: Optional[str] = None,
                          prefix_cache: bool = True,
                          priority: int = 0,
                          adapter_id: Optional[str] = None,
                          grammar=None,
                          resume_after_seq=None) -> dict:
        """Run one or more prompts to completion and return an OpenAI-ish
        response dict. ``prompt`` is a token-id list or a batch of them
        (one ``choices`` entry each, continuous-batched through the
        engine). ``stream_cb(chunk)`` receives OpenAI-chunk-shaped dicts
        as tokens land. Each batch-mate's first token samples from its
        own stream (``seed + index``), so n-best sampling of one prompt
        diverges instead of returning n identical choices. ``deadline_s``
        bounds each choice from enqueue; an expired one comes back with
        ``finish_reason="timeout"`` and whatever tokens it produced.
        ``model=`` selects the tenant on a Router backend (batch-mates
        stay on one engine so they continuous-batch together); unknown
        ids raise an actionable ValueError, a fully gated-out model
        raises :class:`NoHealthyEngineError`. ``prefix_cache=False``
        opts every choice of this call out of the engine's prompt
        prefix cache (docs/SERVING.md "Prefix caching"): full prefill
        from token 0, no page sharing — for prompts that must not be
        indexed (privacy) or A/B-measuring the cache itself.
        ``priority`` is the request's SLO tier (lower = more urgent,
        0 default): it orders admission and prompt-chunk scheduling on
        the engine (docs/SERVING.md "Unified step & chunked prefill"),
        so a latency-tier tenant's prompt chunks preempt a batch tier's
        under a contended token budget. ``adapter_id`` names a LoRA
        adapter every choice decodes through (on a Router backend,
        placement narrows to engines holding it); ``grammar`` is a
        compiled :class:`~.grammar.GrammarFSM` constraining every
        choice's tokens (docs/SERVING.md "Constrained decoding").
        ``resume_after_seq`` is the reconnect half of the exactly-once
        streaming contract (docs/RESILIENCE.md "Durability"): a client
        that saw chunks through seq N before losing its connection
        passes ``resume_after_seq=N`` (an int for every choice, or one
        per choice) and ``stream_cb`` receives only chunks with
        ``seq > N`` — re-submitted deterministic requests (same prompt/
        seed/temperature) regenerate identical tokens, so the suppressed
        prefix is exactly what the client already holds."""
        t0 = time.perf_counter()
        prompts = self._as_batch(prompt)
        try:
            engine, handle, resp_model = self._route(model, adapter_id)
        except (ValueError, NoHealthyEngineError):
            self._m_completions.labels(status="rejected").inc()
            raise
        # validate the WHOLE batch before queueing anything: a rejected
        # later prompt must not strand already-queued batch-mates
        try:
            for p in prompts:
                engine.check_request(p.size, max_tokens)
        except ValueError:
            self._m_completions.labels(status="rejected").inc()
            raise
        cid = f"cmpl-{next(_cmpl_counter)}"
        req_ids = []
        try:
            for idx, p in enumerate(prompts):
                cb = None
                if stream_cb is not None:
                    after = -1
                    if resume_after_seq is not None:
                        after = int(
                            resume_after_seq[idx]
                            if isinstance(resume_after_seq,
                                          (list, tuple, np.ndarray))
                            else resume_after_seq)
                    cb = self._chunk_cb(stream_cb, cid, idx, resp_model,
                                        after_seq=after)
                req_ids.append(engine.add_request(
                    p, max_new_tokens=max_tokens, temperature=temperature,
                    eos_token_id=stop_token_id, seed=seed + idx,
                    stream_cb=cb, deadline_s=deadline_s,
                    prefix_cache=prefix_cache, priority=priority,
                    adapter_id=adapter_id, grammar=grammar))
                if handle is not None:
                    self.router._count_dispatch(handle)
        except Exception:
            # enqueue failed mid-batch (bounded queue filled, or a
            # Request invariant check_request can't see, e.g. an empty
            # prompt): silently un-queue the mates already added — from
            # the caller's perspective this call was never accepted, so
            # no cancelled counters, no terminal stream chunks, no
            # orphans running under the next create_completion
            for rid in req_ids:
                engine.scheduler.remove(rid)
            self._m_completions.labels(status="rejected").inc()
            raise
        if self.router is not None:
            # drive the FLEET: a health-gated drain may move our queued
            # requests to a sibling mid-flight, and their outputs then
            # come from that engine; outputs we don't own go back
            all_outputs = self.router.run()
            ours = set(req_ids)
            outputs = {k: v for k, v in all_outputs.items() if k in ours}
            unclaimed = {k: v for k, v in all_outputs.items()
                         if k not in ours}
            if unclaimed:
                self.router.stash_unclaimed(unclaimed)
        else:
            outputs = engine.run()
        choices = []
        usage_p = usage_c = 0
        for idx, rid in enumerate(req_ids):
            out = outputs[rid]
            ids = list(out.token_ids)
            full = (list(map(int, out.prompt_token_ids)) + ids
                    if echo else ids)
            choices.append({
                "index": idx,
                "token_ids": full,
                "text": (self.detokenize(full)
                         if self.detokenize is not None else None),
                # pass the engine's reason straight through — the
                # resilience reasons ("timeout"/"cancelled"/"nan"/
                # "error"/"unavailable", docs/SERVING.md table) must not
                # be masked as a normal "length" stop
                "finish_reason": out.finish_reason,
            })
            usage_p += int(out.prompt_token_ids.size)
            usage_c += out.n_gen
        self._m_completions.labels(status="ok").inc()
        self._m_latency.observe(time.perf_counter() - t0)
        return {
            "id": cid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": resp_model,
            "choices": choices,
            "usage": {"prompt_tokens": usage_p,
                      "completion_tokens": usage_c,
                      "total_tokens": usage_p + usage_c},
        }

    def _chunk_cb(self, stream_cb, cid, idx, model_name,
                  after_seq: int = -1):
        def cb(req_id, token, finished, seq):
            if int(seq) <= after_seq:
                # reconnect resume: the client already holds this chunk
                # (resume_after_seq cursor) — suppressing it here keeps
                # delivery exactly-once without the engine knowing
                return
            # the engine's terminal callback passes the finish reason
            # (docs/SERVING.md table) as `finished`, so streamed chunks
            # agree with the final response's choices[].finish_reason —
            # and carry the same routed model name as the final response.
            # `seq` is the engine's per-request monotone token sequence
            # number (token chunks: 0-based generated index; terminal
            # chunk: total tokens emitted): after an in-flight migration
            # the adoptive engine resumes at the journaled seq, so a
            # client can VERIFY it saw every token exactly once.
            try:
                stream_cb({
                    "id": cid,
                    "object": "text_completion.chunk",
                    "model": model_name,
                    "choices": [{
                        "index": idx,
                        "token_id": None if token is None else int(token),
                        "seq": int(seq),
                        "finish_reason": finished or None,
                    }],
                })
            except Exception as e:
                # a raising USER callback must never abort the engine
                # step its batch-mates are riding: normalize to
                # CallbackError (original chained) — the engine's
                # callback isolation records it and retires THIS request
                # with finish_reason="error"
                raise faults.CallbackError(
                    f"stream_cb raised for {cid} choice {idx}") from e

        return cb

    @staticmethod
    def _as_batch(prompt) -> List[np.ndarray]:
        if isinstance(prompt, (list, tuple)):
            if not prompt:
                raise ValueError("empty prompt batch")
            if np.ndim(prompt[0]) == 0:  # flat token-id list
                return [np.asarray(prompt, np.int32)]
            # ragged batch: one choices entry per prompt
            return [np.asarray(p, np.int32).reshape(-1) for p in prompt]
        arr = np.asarray(prompt)
        if arr.ndim == 1:
            return [arr.astype(np.int32)]
        if arr.ndim == 2:
            return [row.astype(np.int32) for row in arr]
        raise ValueError(f"prompt rank {arr.ndim} unsupported")
