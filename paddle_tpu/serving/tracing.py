"""Per-request tracing + always-on flight recorder (ISSUE 17).

The request-scoped third leg of observability: metrics (PR 2) aggregate,
the profiler samples inside RECORD windows, and this module journals the
LIFECYCLE of every individual request — always on, so when
BENCH_LOAD.json says interactive TTFT attainment is 0.51 the trace can
say *where* each missed request's milliseconds went (queue wait vs.
chunked prefill vs. compile vs. migration hop) instead of shrugging at
an aggregate histogram.

Design (docs/OBSERVABILITY.md "Request tracing & flight recorder"):

- **Bounded ring buffer.** ``RequestTracer`` preallocates ``capacity``
  mutable slots and overwrites the oldest event when full — the journal
  can never grow the heap on the step path, and the overwrite count
  surfaces as ``paddle_tpu_trace_dropped_events_total`` (flushed lazily:
  the hot path only bumps a local int).
- **Exactly-once keys.** Every event is keyed ``(req_id, seq)`` with a
  per-request monotone ``seq`` assigned by the FLEET-GLOBAL tracer — a
  request that hops engines mid-decode (export → adopt) keeps one seq
  stream, so its timeline merges contiguous across the hop and a
  duplicated or missing event is detectable exactly like a duplicated
  stream chunk (``validate_events``).
- **Injectable monotonic clock.** Defaults to ``time.perf_counter`` —
  the SAME clock domain ``loadgen.LoadDriver`` stamps ``t_submit`` with,
  which is what lets :func:`attribute_ttft` partition a measured TTFT
  exactly (±float error, not ±clock skew).
- **Low overhead.** Disabled tracing is ONE flag check (the metrics
  disabled-registry contract; pinned by tests/test_tracing.py). Enabled,
  ``emit`` mutates a preallocated slot in place — no metric calls, no
  locks, no allocation beyond the interned floats Python itself makes.
- **Flight recorder.** The ring is always armed; ``dump_flight`` writes
  the last ``window_s`` seconds of fleet timeline to disk as JSON. The
  Router calls it from crash containment and on the /healthz ok→degraded
  transition, so a post-mortem starts with the victim requests' full
  timelines already on disk (docs/RESILIENCE.md "Flight recorder").

Threading: ``emit`` rides the engine/router step path, which the serving
contract keeps single-threaded; ``dump_flight`` may fire from the scrape
thread (a /healthz transition) and reads a best-effort snapshot — a slot
mutating mid-dump yields one torn event in a post-mortem file, never a
crash or a lock on the step path.
"""
from __future__ import annotations

import json
import os
import re
import tempfile
import time
from typing import Callable, Dict, List, Optional

from .. import faults, metrics

__all__ = [
    "EVENTS", "RequestTracer", "TTFT_BUCKETS", "attribute_ttft",
    "get_tracer", "set_tracer", "validate_events",
]

faults.declare_point(
    "tracing.dump", "top of RequestTracer.dump_flight, before the ring "
    "snapshot and the post-mortem file write — a raise simulates a full "
    "disk / unwritable flight dir; callers (router crash containment, "
    "/healthz transitions) must treat a failed dump as diagnostics "
    "lost, never as a serving failure")

# The event-name catalog: every literal ``tracer.emit("<name>", ...)``
# site in the package uses one of these, and docs/OBSERVABILITY.md
# tables them — tpulint TPL010 pins both directions. ``req.*`` events
# key on the request id; ``step.*`` events are engine-scoped (their
# req_id is the engine_id string) and render as counter tracks.
EVENTS: Dict[str, str] = {
    "req.enqueue": "request entered an engine queue (arg: prompt tokens)",
    "req.dispatch": "router placed the request (label: engine_id)",
    "req.admit": "parked in a slot (arg: prefix-matched tokens; "
                 "label: engine_id)",
    "req.prefix_hit": "radix prefix-cache hit at admission (arg: "
                      "matched tokens; only emitted when > 0)",
    "req.chunk_planned": "plan_chunks granted this slot a prompt chunk "
                         "(arg: chunk tokens)",
    "req.drafts": "plan_drafts granted speculative draft rows, post "
                  "grammar pre-filter (arg: draft tokens)",
    "req.compile": "a fresh token-grid bucket compiled under this "
                   "request (arg: build+step seconds)",
    "req.chunk": "prompt chunk landed (arg: chunk tokens)",
    "req.spec_accept": "draft burst verified (arg: accepted drafts)",
    "req.spec_reject": "draft burst rolled back via pool.truncate "
                       "(arg: rejected drafts)",
    "req.grammar_mask": "constrained token landed, DFA advanced "
                        "(arg: new FSM state)",
    "req.token": "stream chunk emitted (arg: stream seq)",
    "req.retire": "terminal (label: finish_reason)",
    "req.export": "in-flight journal exported off a dying engine "
                  "(arg: journal length; label: engine_id)",
    "req.adopt": "journal adopted by a sibling engine (arg: journal "
                 "length; label: engine_id)",
    "req.requeue": "waiting request moved to a sibling (label: target "
                   "engine_id)",
    "req.recover": "request re-admitted from the WAL after a process "
                   "restart (arg: journaled tokens; label: adoptive "
                   "engine_id)",
    "req.migrate": "in-flight request migrated to a sibling (label: "
                   "target engine_id)",
    "req.shed": "refused at admission by the overload controller "
                "(arg: predicted wait s; label: cause)",
    "req.preempt": "batch-tier decode slot journaled and requeued by "
                   "the brownout ladder (arg: journal length; label: "
                   "engine_id)",
    "req.expire": "deadline lapsed while still queued — retired "
                  "\"expired\", pages never allocated (label: "
                  "engine_id)",
    "step.tokens": "one engine step (req_id: engine_id; arg: tokens "
                   "landed this step)",
    "brownout.level": "brownout ladder transition (req_id: model_id; "
                      "arg: new level; label: level name)",
}

# TTFT attribution buckets (docs/OBSERVABILITY.md "TTFT attribution"):
# per-request bucket values always sum EXACTLY to the measured TTFT —
# the residual (clock tails, submit overhead, un-journaled gaps from a
# wrapped ring) is pinned into host_overhead rather than dropped.
TTFT_BUCKETS = ("queue", "compile", "cold_prefill", "warm_prefill",
                "decode", "migration", "host_overhead")

_MIGRATION_EVENTS = frozenset(
    ("req.export", "req.adopt", "req.requeue", "req.migrate"))
_DECODE_EVENTS = frozenset(("req.token", "req.grammar_mask",
                            "req.spec_accept", "req.spec_reject"))
_QUEUE_EVENTS = frozenset(("req.admit", "req.prefix_hit"))

_REASON_SAFE_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


class RequestTracer:
    """Always-on bounded event journal keyed ``(req_id, seq)``.

    One process-wide instance (:func:`get_tracer`) serves the whole
    fleet: every engine and the router emit into the same ring, which is
    what makes a migrated request's timeline contiguous — its seq
    counter lives here, not on any engine.
    """

    def __init__(self, capacity: int = 65536,
                 clock: Callable[[], float] = time.perf_counter,
                 enabled: bool = True,
                 flight_dir: Optional[str] = None,
                 window_s: float = 30.0):
        cap = max(int(capacity), 16)
        self._cap = cap
        # preallocated mutable slots [t, req_id, seq, name, arg, label]
        # — emit() writes fields in place, so a full ring never grows
        self._ring: List[list] = [[0.0, None, 0, "", 0.0, ""]
                                  for _ in range(cap)]
        self._head = 0          # next slot to write
        self._count = 0         # filled slots (== cap once wrapped)
        self._seq: Dict[object, int] = {}
        self._dropped = 0       # local; flushed lazily to the counter
        self._dumps = 0
        self.enabled = bool(enabled)
        self._clock = clock
        self.window_s = float(window_s)
        self.flight_dir = flight_dir

    # ------------------------------------------------------------- hot path
    def emit(self, name: str, req_id, arg: float = 0.0, label: str = "",
             t: Optional[float] = None) -> None:
        """Journal one event. Disabled = this flag check; enabled = a
        dict get/set (the per-request seq) plus six in-place slot
        writes. Never raises, never locks, never touches a metric."""
        if not self.enabled:
            return
        if t is None:
            t = self._clock()
        seq = self._seq.get(req_id, 0)
        self._seq[req_id] = seq + 1
        i = self._head
        if self._count < self._cap:
            self._count += 1
        else:
            self._dropped += 1          # overwrote the oldest event
        slot = self._ring[i]
        slot[0] = t
        slot[1] = req_id
        slot[2] = seq
        slot[3] = name
        slot[4] = arg
        slot[5] = label
        self._head = 0 if i + 1 == self._cap else i + 1

    # ------------------------------------------------------------ snapshots
    def events(self) -> List[dict]:
        """Chronological snapshot of the ring as event dicts — the read
        side (attribution, dumps, trace_dump) allocates; the write side
        never does."""
        if self._count < self._cap:
            raw = self._ring[:self._count]
        else:
            raw = self._ring[self._head:] + self._ring[:self._head]
        return [{"t": s[0], "req_id": s[1], "seq": s[2], "name": s[3],
                 "arg": s[4], "label": s[5]} for s in raw]

    def events_for(self, req_id) -> List[dict]:
        """This request's timeline in seq order — contiguous across any
        number of migration hops (one global seq stream per req_id)."""
        out = [e for e in self.events() if e["req_id"] == req_id]
        out.sort(key=lambda e: e["seq"])
        return out

    @property
    def dropped(self) -> int:
        """Events overwritten before any export (local, pre-flush)."""
        return self._dropped

    def reset(self) -> None:
        """Forget everything (benchmark isolation). The ring stays
        allocated; seq counters restart at 0 for every req_id."""
        self._head = 0
        self._count = 0
        self._seq.clear()
        self._dropped = 0

    # -------------------------------------------------------------- metrics
    def flush_metrics(self) -> None:
        """Move the locally-accumulated drop count into the registry —
        called from dump/score/export paths, NEVER per event, so the
        step path stays metric-free."""
        reg = metrics.get_registry()
        dropped = reg.counter(
            "paddle_tpu_trace_dropped_events_total",
            "Trace ring events overwritten before any export read them")
        if self._dropped:
            dropped.inc(self._dropped)
            self._dropped = 0

    # ------------------------------------------------------ flight recorder
    def dump_flight(self, reason: str, path: Optional[str] = None,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None) -> str:
        """Write the last ``window_s`` seconds of fleet timeline to disk
        as JSON (``events`` chronological + ``requests`` grouped per
        req_id in seq order) and return the file path. Callers on the
        serving path guard this — a failed dump loses diagnostics, not
        requests (the armed ``tracing.dump`` fault proves it)."""
        faults.point("tracing.dump")
        if now is None:
            now = self._clock()
        win = self.window_s if window_s is None else float(window_s)
        evs = [e for e in self.events() if e["t"] >= now - win]
        requests: Dict[str, List[dict]] = {}
        for e in evs:
            requests.setdefault(str(e["req_id"]), []).append(e)
        for timeline in requests.values():
            timeline.sort(key=lambda e: e["seq"])
        payload = {"reason": str(reason), "t_dump": now, "window_s": win,
                   "dropped_events": self._dropped,
                   "events": evs, "requests": requests}
        if path is None:
            d = (self.flight_dir
                 or os.environ.get("PADDLE_TPU_FLIGHT_DIR")
                 or os.path.join(tempfile.gettempdir(),
                                 "paddle_tpu_flight"))
            os.makedirs(d, exist_ok=True)
            self._dumps += 1
            safe = _REASON_SAFE_RE.sub("-", str(reason)) or "dump"
            path = os.path.join(
                d, f"flight-{os.getpid()}-{self._dumps:03d}-{safe}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
        reg = metrics.get_registry()
        reg.counter("paddle_tpu_trace_recorder_dumps_total",
                    "Flight-recorder dumps by trigger",
                    labels=("reason",)).labels(reason=str(reason)).inc()
        self.flush_metrics()
        return path


def validate_events(events: List[dict]) -> List[str]:
    """Exactly-once audit of one request's timeline: every ``(req_id,
    seq)`` unique, seqs contiguous from the smallest captured one (a
    wrapped ring legitimately loses the OLDEST prefix, never punches a
    hole). Returns human-readable problems; [] is the pass."""
    problems: List[str] = []
    by_req: Dict[object, List[int]] = {}
    for e in events:
        by_req.setdefault(e["req_id"], []).append(int(e["seq"]))
    for rid, seqs in sorted(by_req.items(), key=lambda kv: str(kv[0])):
        seqs.sort()
        dupes = sorted({s for i, s in enumerate(seqs)
                        if i and seqs[i - 1] == s})
        if dupes:
            problems.append(f"req {rid}: duplicate seq(s) {dupes}")
        want = list(range(seqs[0], seqs[0] + len(seqs)))
        if not dupes and seqs != want:
            missing = sorted(set(want) - set(seqs))[:8]
            problems.append(f"req {rid}: missing seq(s) {missing}")
    return problems


def attribute_ttft(events: List[dict], t_submit: float,
                   t_first: float) -> Dict[str, float]:
    """Decompose one request's measured TTFT into :data:`TTFT_BUCKETS`.

    Partition ``(t_submit, t_first]`` at the request's trace events and
    charge each gap to the bucket of the event that ENDS it: the wait
    that ended in admission was queue time, the wait that ended in a
    chunk landing was prefill (warm when a prefix-cache hit covered part
    of the prompt, cold otherwise), the wait that ended in a fresh-
    bucket compile was compile, a migration-hop event charges its gap to
    migration. Whatever the events don't cover — submit overhead, the
    tail after the last event, timelines truncated by ring wrap — lands
    in ``host_overhead`` as the exact residual, so::

        sum(attribute_ttft(...).values()) == t_first - t_submit

    holds to float precision (the BENCH_LOAD ±1 ms acceptance bound is
    slack, not a fudge factor).
    """
    out = {b: 0.0 for b in TTFT_BUCKETS}
    measured = t_first - t_submit
    window = [e for e in events if t_submit < e["t"] <= t_first]
    window.sort(key=lambda e: e["seq"])
    warm = any(e["name"] == "req.prefix_hit" for e in window)
    prev = t_submit
    classified = 0.0
    for e in window:
        gap = e["t"] - prev
        prev = e["t"]
        if gap <= 0.0:
            continue
        name = e["name"]
        if name in _QUEUE_EVENTS:
            bucket = "queue"
        elif name == "req.compile":
            bucket = "compile"
        elif name == "req.chunk":
            bucket = "warm_prefill" if warm else "cold_prefill"
        elif name in _DECODE_EVENTS:
            bucket = "decode"
        elif name in _MIGRATION_EVENTS:
            bucket = "migration"
        else:
            # enqueue/dispatch/plan decisions: host bookkeeping
            bucket = "host_overhead"
        out[bucket] += gap
        classified += gap
    out["host_overhead"] += measured - classified
    return out


# --------------------------------------------------------- default tracer
_default_tracer: Optional[RequestTracer] = None


def get_tracer() -> RequestTracer:
    """The process-wide tracer every engine/router/driver shares —
    created on first use from the env knobs (docs/SERVING.md "Tracing
    knobs"): ``PADDLE_TPU_TRACE=0`` disables, ``PADDLE_TPU_TRACE_
    CAPACITY`` sizes the ring, ``PADDLE_TPU_FLIGHT_DIR`` /
    ``PADDLE_TPU_FLIGHT_WINDOW_S`` steer the flight recorder."""
    global _default_tracer
    if _default_tracer is None:
        _default_tracer = RequestTracer(
            capacity=int(os.environ.get("PADDLE_TPU_TRACE_CAPACITY",
                                        "65536") or 65536),
            enabled=os.environ.get("PADDLE_TPU_TRACE", "1") != "0",
            flight_dir=os.environ.get("PADDLE_TPU_FLIGHT_DIR"),
            window_s=float(os.environ.get("PADDLE_TPU_FLIGHT_WINDOW_S",
                                          "30") or 30.0))
    return _default_tracer


def set_tracer(tracer: Optional[RequestTracer]) -> \
        Optional[RequestTracer]:
    """Swap the process-wide tracer (tests inject a virtual clock or a
    tiny ring); returns the previous one. ``None`` resets to lazy env
    construction."""
    global _default_tracer
    old = _default_tracer
    _default_tracer = tracer
    return old
