"""Request write-ahead log — the durability layer under
``Router(wal_dir=...)`` (docs/RESILIENCE.md "Durability").

Every robustness guarantee the fleet had before this module lived inside
one Python process: the ``Request.resume_tokens`` journal, the grammar
FSM state, the stream seq cursor — all heap state, all gone on SIGKILL.
This module makes the request plane itself durable: an append-only,
CRC-framed, fsync-disciplined log that journals

* each request's **admission record** (prompt ids, seed, priority,
  deadline + wall-clock admission time, adapter_id, grammar spec key,
  prefix_cache flag),
* every **committed token batch** (the ``resume_tokens`` journal delta +
  the stream seq cursor + the grammar ``resume_fsm_state``), and
* terminal **retirement** (finish reason).

On restart ``Router.recover()`` replays the log (a pure function —
replay twice ⇒ the same state), re-admits unfinished work through the
existing journaled re-prefill path (``engine.adopt_request``) onto
whatever engines the restarted fleet has, and resumes emission at the
journaled seq — the same determinism contract that makes in-process
migration invisible (tokens are a pure function of (prompt, seed,
temperature)) makes process death invisible too.

Disk format: segments ``wal-<n>.log`` of ``<u32 len><u32 crc32(payload)>
<payload>`` frames, payload JSON. Appends are **group-committed**: the
router buffers records across one ``router.step()`` and pays ONE
``fsync`` per step, not per token. On open, a torn tail (partial frame,
CRC mismatch — the bytes a crash left mid-write) is truncated away and
counted in ``paddle_tpu_wal_corrupt_records_total``; everything before
it is trusted. Segments rotate at ``segment_bytes``; rotation compacts
once enough retired requests have accumulated — live requests are
rewritten as one admit + one progress record into a fresh segment via
the tmp + fsync + rename idiom (framework/io.py), retired history is
dropped.

Fault points: ``wal.append`` / ``wal.fsync`` / ``wal.replay``.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from .. import faults, metrics

__all__ = ["RequestWAL", "WalRequest", "WalState", "RECORD_KINDS"]

_HDR = struct.Struct("<II")          # (payload length, crc32(payload))
_MAX_RECORD = 1 << 26                # sanity bound on one frame's length
_SEG_PREFIX, _SEG_SUFFIX = "wal-", ".log"

#: every record kind the log can carry — ``admit`` opens a request,
#: ``progress`` extends its committed token journal, ``retire`` closes
#: it, ``recover`` marks an old incarnation superseded by a re-admitted
#: one, ``seal`` marks a clean shutdown (graceful drain, nothing torn).
RECORD_KINDS = ("admit", "progress", "retire", "recover", "seal")

faults.declare_point(
    "wal.append", "framing one record into the WAL's group-commit "
    "buffer — a raise simulates an allocation/serialization failure "
    "before any byte is durable; the router must surface it to the "
    "submitter, never half-journal a request")
faults.declare_point(
    "wal.fsync", "the ONE durability barrier of a group commit, after "
    "the buffered frames are written and before fsync — a raise "
    "simulates a full disk / dying device; committed state stays "
    "whatever the LAST successful fsync covered")
faults.declare_point(
    "wal.replay", "top of RequestWAL.replay(), before any segment is "
    "read — a raise simulates an unreadable log directory; recovery "
    "must fail loudly (no silent empty-state restart)")


@dataclass
class WalRequest:
    """One request's durable state, folded from its log records."""

    wal_id: int
    model: Optional[str] = None
    prompt: List[int] = field(default_factory=list)
    max_new_tokens: int = 0
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    priority: int = 0
    deadline_s: Optional[float] = None
    admit_walltime: float = 0.0          # time.time() at admission
    adapter_id: Optional[str] = None
    grammar_key: Optional[Tuple[str, int, Optional[int]]] = None
    prefix_cache: bool = True
    resume_from: Optional[int] = None    # wal_id this one re-admitted
    tokens: List[int] = field(default_factory=list)  # committed journal
    fsm_state: Optional[int] = None      # valid for exactly `tokens`
    outcome: Optional[str] = None        # finish_reason once retired
    superseded_by: Optional[int] = None  # recover record's new wal_id

    @property
    def live(self) -> bool:
        """Admitted, not retired, not superseded — recovery's work set."""
        return self.outcome is None and self.superseded_by is None


class WalState:
    """The fold of a record stream — what :meth:`RequestWAL.replay`
    returns. Building it is pure: replaying the same log twice yields
    equal states (the idempotence property tests/test_wal.py pins)."""

    def __init__(self):
        self.requests: Dict[int, WalRequest] = {}
        self.next_wal_id: int = 0
        self.sealed: bool = False        # last record was a clean seal
        self.records: int = 0

    def apply(self, rec: dict) -> None:
        self.records += 1
        kind = rec.get("k")
        self.sealed = kind == "seal"
        if kind == "admit":
            wid = int(rec["id"])
            self.next_wal_id = max(self.next_wal_id, wid + 1)
            self.requests[wid] = WalRequest(
                wal_id=wid, model=rec.get("model"),
                prompt=[int(t) for t in rec.get("prompt", ())],
                max_new_tokens=int(rec.get("max_new_tokens", 0)),
                temperature=float(rec.get("temperature", 0.0)),
                eos_token_id=rec.get("eos"),
                seed=int(rec.get("seed", 0)),
                priority=int(rec.get("priority", 0)),
                deadline_s=rec.get("deadline_s"),
                admit_walltime=float(rec.get("t", 0.0)),
                adapter_id=rec.get("adapter_id"),
                grammar_key=(tuple(rec["grammar"])
                             if rec.get("grammar") else None),
                prefix_cache=bool(rec.get("prefix_cache", True)),
                resume_from=rec.get("resume_from"),
                tokens=[int(t) for t in rec.get("tokens", ())],
                fsm_state=rec.get("fsm"))
        elif kind == "progress":
            r = self.requests.get(rec.get("id"))
            if r is None or r.outcome is not None:
                return                       # orphan delta: tolerate
            at = int(rec.get("at", len(r.tokens)))
            toks = [int(t) for t in rec.get("tokens", ())]
            if at <= len(r.tokens):
                # overlap (a replayed delta) extends only the new tail;
                # a gap (at > len — a mid-log corruption hole) is
                # dropped: deterministic decode regenerates the journal
                # identically from the shorter prefix
                r.tokens.extend(toks[len(r.tokens) - at:])
                if at + len(toks) == len(r.tokens):
                    r.fsm_state = rec.get("fsm")
        elif kind == "retire":
            r = self.requests.get(rec.get("id"))
            if r is not None and r.outcome is None:
                r.outcome = str(rec.get("reason", "error"))
        elif kind == "recover":
            r = self.requests.get(rec.get("old"))
            if r is not None and r.superseded_by is None:
                r.superseded_by = int(rec["new"])

    def pending(self) -> List[WalRequest]:
        """Admitted-but-unfinished requests in admission order — the
        exact set a restarted router must re-admit."""
        return sorted((r for r in self.requests.values() if r.live),
                      key=lambda r: r.wal_id)


def _fsync_dir(path: str) -> None:
    """Directory-entry durability for rotate/compact renames (the same
    best-effort idiom as framework/io.py)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class RequestWAL:
    """Append-only request log with group commit (see module docstring).

    ::

        wal = RequestWAL(wal_dir)
        wal.append("admit", id=wal.new_id(), prompt=[...], seed=7, ...)
        ...                     # buffered — nothing durable yet
        wal.commit()            # ONE write + ONE fsync for the batch
        state = wal.replay()    # pure fold of the on-disk records

    The writer side (append/commit/seal) belongs to the router's step
    loop; the reader side (replay) is what ``Router.recover()`` calls
    after a crash. Both may be used on the same live instance — replay
    reads only committed bytes.
    """

    def __init__(self, wal_dir: str, segment_bytes: int = 1 << 20,
                 compact_retired: int = 256):
        self.dir = str(wal_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.segment_bytes = int(segment_bytes)
        self.compact_retired = int(compact_retired)
        self._buf: List[bytes] = []     # framed records awaiting commit
        self._fh = None
        self._active_size = 0
        self._retired_since_compact = 0
        reg = metrics.get_registry()
        self._m_append = reg.histogram(
            "paddle_tpu_wal_append_seconds",
            "Framing one record (CRC + JSON) into the group-commit "
            "buffer — the per-record cost the submit/step hot path pays")
        self._m_fsync = reg.histogram(
            "paddle_tpu_wal_fsync_seconds",
            "One group commit's durability barrier: buffered frames "
            "written + ONE fsync (per router.step(), not per token)")
        self._m_replay = reg.histogram(
            "paddle_tpu_wal_replay_seconds",
            "Full log replay: every segment read, CRC-checked and "
            "folded into a WalState (the recovery critical path)")
        self._m_records = reg.counter(
            "paddle_tpu_wal_records_total",
            "WAL records appended, by kind (admit / progress / retire / "
            "recover / seal)", labels=("kind",))
        for k in RECORD_KINDS:
            self._m_records.labels(kind=k)   # pre-create: scrapes show 0
        self._m_corrupt = reg.counter(
            "paddle_tpu_wal_corrupt_records_total",
            "Torn or corrupt WAL frames discarded at open (partial "
            "header, short payload, CRC mismatch) — the tail a crash "
            "left mid-write, truncated away before replay")
        self._open()

    # ------------------------------------------------------------- segments
    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith(_SEG_PREFIX)
                           and n.endswith(_SEG_SUFFIX))
        except OSError:
            names = []
        return [os.path.join(self.dir, n) for n in names]

    @staticmethod
    def _seg_index(path: str) -> int:
        name = os.path.basename(path)
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])

    def _seg_path(self, idx: int) -> str:
        return os.path.join(self.dir, f"{_SEG_PREFIX}{idx:08d}{_SEG_SUFFIX}")

    def _open(self) -> None:
        """Scan every segment, truncate any torn tail (counting the
        discarded frames), seed the id allocator from a first replay,
        and open the newest segment for append."""
        segs = self._segments()
        for path in segs:
            good, total, corrupt = self._scan(path)
            if good < total:
                self._m_corrupt.inc(max(corrupt, 1))
                with open(path, "r+b") as f:
                    f.truncate(good)
                    f.flush()
                    os.fsync(f.fileno())
                _fsync_dir(self.dir)
        if not segs:
            segs = [self._seg_path(0)]
            with open(segs[0], "ab"):
                pass
            _fsync_dir(self.dir)
        state = self.replay()
        self._next_wal_id = state.next_wal_id
        self._retired_since_compact = sum(
            1 for r in state.requests.values() if not r.live)
        active = segs[-1]
        self._fh = open(active, "ab")
        self._active_size = os.path.getsize(active)

    def _scan(self, path: str) -> Tuple[int, int, int]:
        """(good_bytes, total_bytes, corrupt_frames) for one segment —
        the torn-tail detector. Never raises on bad bytes."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return 0, 0, 0
        good, corrupt = 0, 0
        for _rec, end in self._iter_frames(data):
            if _rec is None:
                corrupt += 1
                break
            good = end
        if good < len(data) and corrupt == 0:
            corrupt = 1                  # trailing partial header
        return good, len(data), corrupt

    @staticmethod
    def _iter_frames(data: bytes) -> Iterator[Tuple[Optional[dict], int]]:
        """Yield (record, end_offset) per frame; (None, off) once on the
        first torn/corrupt frame, then stop — nothing after an
        undecodable frame can be trusted."""
        off, n = 0, len(data)
        while off + _HDR.size <= n:
            ln, crc = _HDR.unpack_from(data, off)
            end = off + _HDR.size + ln
            if ln > _MAX_RECORD or end > n:
                yield None, off
                return
            payload = data[off + _HDR.size:end]
            if zlib.crc32(payload) != crc:
                yield None, off
                return
            try:
                rec = json.loads(payload.decode())
            except (ValueError, UnicodeDecodeError):
                yield None, off
                return
            yield rec, end
            off = end

    # ------------------------------------------------------------ writer
    def new_id(self) -> int:
        """Allocate a durable request id. ``Request.req_id`` restarts
        with the process (a plain itertools counter), so the WAL owns
        the identity that survives death."""
        wid, self._next_wal_id = self._next_wal_id, self._next_wal_id + 1
        return wid

    def append(self, kind: str, **payload) -> None:
        """Frame one record into the group-commit buffer. NOTHING is
        durable until :meth:`commit` — the buffer is the group-commit
        window (one router step)."""
        t0 = time.perf_counter()
        faults.point("wal.append")
        payload["k"] = kind
        data = json.dumps(payload, separators=(",", ":")).encode()
        self._buf.append(_HDR.pack(len(data), zlib.crc32(data)) + data)
        if kind in ("retire", "recover"):
            self._retired_since_compact += 1
        self._m_records.labels(kind=kind).inc()
        self._m_append.observe(time.perf_counter() - t0)

    def commit(self) -> int:
        """Write every buffered frame and fsync ONCE; returns the number
        of records made durable. Empty buffer = no write, no fsync —
        idle steps stay free. Rotates (and maybe compacts) afterwards
        so the barrier itself never waits on a rewrite."""
        if not self._buf:
            return 0
        frames, self._buf = self._buf, []
        blob = b"".join(frames)
        t0 = time.perf_counter()
        self._fh.write(blob)
        self._fh.flush()
        faults.point("wal.fsync")
        os.fsync(self._fh.fileno())
        self._m_fsync.observe(time.perf_counter() - t0)
        self._active_size += len(blob)
        if self._active_size >= self.segment_bytes:
            self._rotate()
        return len(frames)

    def seal(self) -> None:
        """Clean-shutdown marker: append + commit a ``seal`` record.
        ``replay().sealed`` then tells the next process the previous one
        drained and exited on purpose — nothing pending, nothing torn."""
        self.append("seal")
        self.commit()

    def close(self) -> None:
        """Commit anything buffered and drop the file handle. NOT a
        seal: a closed-but-unsealed log reads as a crash, which is
        exactly right for teardown paths that didn't drain."""
        if self._fh is not None:
            self.commit()
            self._fh.close()
            self._fh = None

    def _rotate(self) -> None:
        """Start a fresh segment (append never straddles — a commit's
        frames land in one file); compact first if enough retired
        history has piled up."""
        if self._retired_since_compact >= self.compact_retired:
            self.compact()
            return
        self._fh.close()
        idx = self._seg_index(self._segments()[-1]) + 1
        path = self._seg_path(idx)
        self._fh = open(path, "ab")
        self._active_size = 0
        _fsync_dir(self.dir)

    def compact(self) -> None:
        """Drop retired history: fold the whole log, rewrite only LIVE
        requests (one admit carrying the accumulated journal each) into
        a fresh segment via tmp + fsync + rename, then delete the old
        segments. Crash-safe at every point: until the rename lands the
        old segments are the log; after it they are garbage a later
        open ignores (the new segment sorts last and replay folds
        admits idempotently)."""
        state = self.replay()
        idx = self._seg_index(self._segments()[-1]) + 1
        path = self._seg_path(idx)
        tmp = f"{path}.tmp-{os.getpid()}"
        frames = []
        for r in state.pending():
            rec = {"k": "admit", "id": r.wal_id, "model": r.model,
                   "prompt": r.prompt,
                   "max_new_tokens": r.max_new_tokens,
                   "temperature": r.temperature, "eos": r.eos_token_id,
                   "seed": r.seed, "priority": r.priority,
                   "deadline_s": r.deadline_s, "t": r.admit_walltime,
                   "adapter_id": r.adapter_id,
                   "grammar": (list(r.grammar_key)
                               if r.grammar_key else None),
                   "prefix_cache": r.prefix_cache,
                   "resume_from": r.resume_from,
                   "tokens": r.tokens, "fsm": r.fsm_state}
            data = json.dumps(rec, separators=(",", ":")).encode()
            frames.append(_HDR.pack(len(data), zlib.crc32(data)) + data)
        old = self._segments()
        if self._fh is not None:
            self._fh.close()
        try:
            with open(tmp, "wb") as f:
                f.write(b"".join(frames))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
            raise
        _fsync_dir(self.dir)
        for p in old:
            try:
                os.unlink(p)
            except OSError:
                pass
        _fsync_dir(self.dir)
        self._fh = open(path, "ab")
        self._active_size = os.path.getsize(path)
        self._retired_since_compact = 0

    # ------------------------------------------------------------ reader
    def replay(self) -> WalState:
        """Fold every committed record into a :class:`WalState`. Pure:
        no writer state is touched, and replaying twice yields equal
        states. Torn tails were already truncated at :meth:`_open`; a
        frame that went bad since (bit rot) stops that segment's fold
        at the last good frame — never raises on bad bytes."""
        t0 = time.perf_counter()
        faults.point("wal.replay")
        state = WalState()
        for path in self._segments():
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            for rec, _end in self._iter_frames(data):
                if rec is None:
                    break
                state.apply(rec)
        self._m_replay.observe(time.perf_counter() - t0)
        return state
