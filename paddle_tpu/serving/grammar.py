"""Constrained decoding: regex / JSON-schema grammars compiled to a
token-level DFA (ISSUE 16).

A :class:`GrammarFSM` turns a regex (or a small JSON-schema subset,
lowered to a regex first) into a dense ``[n_states, vocab]`` boolean
allow-mask plus a ``[n_states, vocab]`` transition table. The mask is
what rides the compiled serving step as DATA — gathered per sample row
and applied as a logit mask — while the transition table is what the
HOST uses to advance each slot's integer FSM state on every landed
token (docs/SERVING.md "Constrained decoding"). Nothing in here ever
touches the compiled program: states are ints, masks are arrays, and
the identity row (all-``True``) that unconstrained slots point at lives
in the engine, not here.

The DFA is built the classic way — Thompson construction to an
epsilon-NFA, subset construction to a DFA, dead-state pruning — over
the printable-ASCII alphabet. A token is allowed in state ``s`` iff
walking its (non-empty) decoded string from ``s`` never leaves the live
DFA; the eos column is allowed exactly in accepting states, so a
constrained stream can only terminate on a complete structure.

Determinism contract: ``compile`` is a pure function of
``(pattern, tokenizer)`` — every engine that compiles the same grammar
against the same tokenizer builds bit-equal tables, which is what lets
a migrated request resume its journaled FSM state on a sibling engine
and continue the identical stream (docs/RESILIENCE.md).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GrammarFSM", "ToyTokenizer", "toy_tokenizer",
           "schema_to_regex"]

# the grammar alphabet: printable ASCII. Tokens whose decoded strings
# step outside it simply never match a literal/class and are masked.
_ALPHABET = frozenset(chr(c) for c in range(32, 127))
_DIGITS = frozenset("0123456789")
_WORD = frozenset("abcdefghijklmnopqrstuvwxyz"
                  "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t")
_META = set("\\.[](){}|*+?")


# --------------------------------------------------------------- tokenizer
class ToyTokenizer:
    """The simplest tokenizer that exercises the whole constrained
    path: token id ``i`` decodes to the single printable character
    ``chr(32 + i % 95)`` (ids past one alphabet cycle repeat it), and an
    optional ``eos_token_id`` decodes to the empty string so it can
    never satisfy a character transition — only the accepting-state eos
    column admits it. Tests, loadgen, and the bench drill all constrain
    tiny random-token models through this mapping."""

    def __init__(self, vocab_size: int, eos_token_id: Optional[int] = None):
        self.vocab_size = int(vocab_size)
        self.eos_token_id = eos_token_id

    def decode_token(self, token_id: int) -> str:
        if self.eos_token_id is not None and token_id == self.eos_token_id:
            return ""
        return chr(32 + (int(token_id) % 95))

    def encode(self, text: str) -> List[int]:
        """Inverse of :meth:`decode_token` (first alphabet cycle)."""
        return [ord(ch) - 32 for ch in text]


def toy_tokenizer(vocab_size: int,
                  eos_token_id: Optional[int] = None) -> ToyTokenizer:
    """One printable character per token id — see :class:`ToyTokenizer`."""
    return ToyTokenizer(vocab_size, eos_token_id)


# ------------------------------------------------------- schema lowering
def _lit(text: str) -> str:
    """Regex-escape a literal string against THIS module's parser."""
    return "".join("\\" + ch if ch in _META else ch for ch in text)


def schema_to_regex(schema: dict) -> str:
    """Lower a small JSON-schema subset to a regex this module parses.

    Supported: ``type`` string / integer / number / boolean / null,
    ``enum`` / ``const`` (JSON-dumped alternation), ``object`` with
    ``properties`` emitted in declaration order (all treated required —
    constrained decoding needs ONE canonical serialization), bounded
    ``array`` (``maxItems`` required, default 3). The emitted language
    is real JSON: every accepted string round-trips through
    ``json.loads``."""
    if "const" in schema:
        return _lit(json.dumps(schema["const"], separators=(",", ":")))
    if "enum" in schema:
        alts = "|".join(_lit(json.dumps(v, separators=(",", ":")))
                        for v in schema["enum"])
        return "(" + alts + ")"
    t = schema.get("type")
    if t == "string":
        # quote-and-backslash-free body keeps the DFA tiny and the
        # output trivially valid JSON
        n = int(schema.get("maxLength", 8))
        return '"[a-z]{0,%d}"' % n
    if t == "integer":
        return "-?(0|[1-9][0-9]{0,3})"
    if t == "number":
        return "-?(0|[1-9][0-9]{0,3})(\\.[0-9]{1,3})?"
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "object":
        props = schema.get("properties", {})
        parts = [_lit(json.dumps(k)) + ":" + schema_to_regex(v)
                 for k, v in props.items()]
        return "\\{" + _lit(",").join(parts) + "\\}"
    if t == "array":
        item = schema_to_regex(schema.get("items", {"type": "integer"}))
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", 3))
        if hi < 1 or hi < lo:
            raise ValueError("array bounds must satisfy 0 <= minItems "
                             f"<= maxItems >= 1, got [{lo}, {hi}]")
        body = "(%s)(,(%s)){%d,%d}" % (item, item, max(lo - 1, 0), hi - 1)
        if lo == 0:
            body = "(" + body + ")?"
        return "\\[" + body + "\\]"
    raise ValueError(f"unsupported schema: {schema!r} — supported types: "
                     "string/integer/number/boolean/null/object/array, "
                     "enum, const")


# ----------------------------------------------------------- regex parser
class _Parser:
    """Recursive-descent regex parser over the printable-ASCII
    alphabet. Supported syntax: literals, ``.``, classes ``[a-z0-9]``
    (with ``^`` negation and escapes), escapes (``\\d \\w \\s`` and
    ``\\<meta>``), groups, ``|``, ``* + ?``, ``{m}`` / ``{m,n}``. AST
    nodes are plain tuples."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _err(self, msg: str):
        raise ValueError(f"regex error at index {self.i} in "
                         f"{self.p!r}: {msg}")

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            self._err("unconsumed input (unbalanced ')'?)")
        return node

    def _alt(self):
        branches = [self._concat()]
        while self.i < len(self.p) and self.p[self.i] == "|":
            self.i += 1
            branches.append(self._concat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _concat(self):
        items = []
        while self.i < len(self.p) and self.p[self.i] not in "|)":
            items.append(self._repeat())
        if not items:
            return ("eps",)
        return items[0] if len(items) == 1 else ("cat", items)

    def _repeat(self):
        node = self._atom()
        while self.i < len(self.p) and self.p[self.i] in "*+?{":
            ch = self.p[self.i]
            if ch == "*":
                node, self.i = ("star", node), self.i + 1
            elif ch == "+":
                node, self.i = ("cat", [node, ("star", node)]), self.i + 1
            elif ch == "?":
                node, self.i = ("alt", [node, ("eps",)]), self.i + 1
            else:
                node = self._bounded(node)
        return node

    def _bounded(self, node):
        j = self.p.index("}", self.i)
        body = self.p[self.i + 1:j]
        self.i = j + 1
        lo_s, _, hi_s = body.partition(",")
        lo = int(lo_s)
        hi = lo if not _ else (int(hi_s) if hi_s else None)
        if hi is not None and hi < lo:
            self._err(f"bad bounds {{{body}}}")
        items = [node] * lo
        if hi is None:
            items.append(("star", node))
        else:
            items.extend([("alt", [node, ("eps",)])] * (hi - lo))
        if not items:
            return ("eps",)
        return items[0] if len(items) == 1 else ("cat", items)

    def _atom(self):
        ch = self.p[self.i]
        if ch == "(":
            self.i += 1
            node = self._alt()
            if self.i >= len(self.p) or self.p[self.i] != ")":
                self._err("unbalanced '('")
            self.i += 1
            return node
        if ch == "[":
            return ("set", self._charclass())
        if ch == ".":
            self.i += 1
            return ("set", _ALPHABET)
        if ch == "\\":
            return ("set", self._escape())
        if ch in "*+?{":
            self._err(f"dangling quantifier {ch!r}")
        self.i += 1
        return ("set", frozenset(ch))

    def _escape(self) -> frozenset:
        self.i += 1
        if self.i >= len(self.p):
            self._err("dangling backslash")
        ch = self.p[self.i]
        self.i += 1
        table = {"d": _DIGITS, "w": _WORD, "s": _SPACE,
                 "t": frozenset("\t"), "n": frozenset()}
        if ch in table:
            return table[ch]
        return frozenset(ch)

    def _charclass(self) -> frozenset:
        self.i += 1  # consume '['
        negate = self.i < len(self.p) and self.p[self.i] == "^"
        if negate:
            self.i += 1
        chars: set = set()
        while self.i < len(self.p) and self.p[self.i] != "]":
            if self.p[self.i] == "\\":
                chars |= self._escape()
                continue
            ch = self.p[self.i]
            if (self.i + 2 < len(self.p) and self.p[self.i + 1] == "-"
                    and self.p[self.i + 2] != "]"):
                lo, hi = ord(ch), ord(self.p[self.i + 2])
                if hi < lo:
                    self._err(f"bad range {ch}-{self.p[self.i + 2]}")
                chars |= {chr(c) for c in range(lo, hi + 1)}
                self.i += 3
            else:
                chars.add(ch)
                self.i += 1
        if self.i >= len(self.p):
            self._err("unbalanced '['")
        self.i += 1  # consume ']'
        out = frozenset(chars)
        return frozenset(_ALPHABET - out) if negate else out


# ---------------------------------------------------------- NFA/DFA build
def _nfa(node, trans: List[Dict[str, set]], eps: List[set]) -> Tuple[int, int]:
    """Thompson construction: returns (start, accept) state ids,
    appending fresh states to ``trans``/``eps``."""
    def new() -> int:
        trans.append({})
        eps.append(set())
        return len(trans) - 1

    kind = node[0]
    if kind == "eps":
        s = new()
        return s, s
    if kind == "set":
        s, e = new(), new()
        for ch in node[1]:
            trans[s].setdefault(ch, set()).add(e)
        return s, e
    if kind == "cat":
        s, e = _nfa(node[1][0], trans, eps)
        for child in node[1][1:]:
            cs, ce = _nfa(child, trans, eps)
            eps[e].add(cs)
            e = ce
        return s, e
    if kind == "alt":
        s, e = new(), new()
        for child in node[1]:
            cs, ce = _nfa(child, trans, eps)
            eps[s].add(cs)
            eps[ce].add(e)
        return s, e
    if kind == "star":
        cs, ce = _nfa(node[1], trans, eps)
        s, e = new(), new()
        eps[s] |= {cs, e}
        eps[ce] |= {cs, e}
        return s, e
    raise AssertionError(f"unknown node {kind!r}")


def _closure(states: frozenset, eps: List[set]) -> frozenset:
    stack, seen = list(states), set(states)
    while stack:
        for nxt in eps[stack.pop()]:
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return frozenset(seen)


def _dfa(pattern: str) -> Tuple[List[Dict[str, int]], set]:
    """regex → (dfa transitions, accepting set); start state is 0, dead
    (can't-reach-accepting) states pruned so "has a transition" means
    "can still complete"."""
    ast = _Parser(pattern).parse()
    trans: List[Dict[str, set]] = []
    eps: List[set] = []
    ns, ne = _nfa(ast, trans, eps)

    start = _closure(frozenset([ns]), eps)
    ids: Dict[frozenset, int] = {start: 0}
    dtrans: List[Dict[str, int]] = [{}]
    work = [start]
    while work:
        cur = work.pop()
        ci = ids[cur]
        by_char: Dict[str, set] = {}
        for st in cur:
            for ch, dsts in trans[st].items():
                by_char.setdefault(ch, set()).update(dsts)
        for ch, dsts in by_char.items():
            nxt = _closure(frozenset(dsts), eps)
            if nxt not in ids:
                ids[nxt] = len(dtrans)
                dtrans.append({})
                work.append(nxt)
            dtrans[ci][ch] = ids[nxt]
    accepting = {i for s, i in ids.items() if ne in s}

    # prune states that cannot reach an accepting state: transitions
    # into them become dead edges, so a token leading there is masked
    # instead of stranding the stream in an uncompletable corner
    live = set(accepting)
    changed = True
    while changed:
        changed = False
        for i, row in enumerate(dtrans):
            if i not in live and any(d in live for d in row.values()):
                live.add(i)
                changed = True
    if 0 not in live:
        raise ValueError(f"regex {pattern!r} matches nothing")
    remap = {old: new for new, old in
             enumerate(sorted(live, key=lambda s: (s != 0, s)))}
    pruned = [{ch: remap[d] for ch, d in dtrans[old].items() if d in live}
              for old in sorted(live, key=lambda s: (s != 0, s))]
    return pruned, {remap[a] for a in accepting if a in live}


# ---------------------------------------------------------------- the FSM
class GrammarFSM:
    """A compiled token-level grammar: dense allow-mask + transition
    table over a fixed tokenizer. Build with :meth:`compile`; the
    engine interns ``mask_table`` into its device-resident grammar
    table and keeps per-slot LOCAL states that this class advances."""

    def __init__(self, pattern: str, tokenizer, dtrans, accepting):
        self.pattern = pattern
        self.vocab_size = int(tokenizer.vocab_size)
        self.eos_token_id = getattr(tokenizer, "eos_token_id", None)
        self._accepting = frozenset(accepting)
        n, v = len(dtrans), self.vocab_size
        # token_next[s, t]: DFA state after token t's decoded string, or
        # -1 if any step dies. Empty strings never transition: only the
        # eos column (accepting states) admits the eos id.
        self.token_next = np.full((n, v), -1, np.int32)
        self.mask_table = np.zeros((n, v), bool)
        strings = [tokenizer.decode_token(t) for t in range(v)]
        for s in range(n):
            for t, w in enumerate(strings):
                if not w:
                    continue
                cur = s
                for ch in w:
                    cur = dtrans[cur].get(ch, -1)
                    if cur < 0:
                        break
                if cur >= 0:
                    self.token_next[s, t] = cur
                    self.mask_table[s, t] = True
        if self.eos_token_id is not None:
            for s in self._accepting:
                self.mask_table[s, self.eos_token_id] = True
        # fail FAST on tokenizer/grammar mismatch: a live non-accepting
        # state with no allowed token would force sampling over a fully
        # masked row — uniform garbage instead of a constraint
        for s in range(n):
            if not self.mask_table[s].any() and s not in self._accepting:
                raise ValueError(
                    f"grammar {pattern!r} state {s} allows no token under "
                    "this tokenizer — the tokenizer does not cover the "
                    "grammar's alphabet")

    # the interning key: two requests carrying equal-pattern grammars
    # over the same vocab share ONE table segment in the engine
    @property
    def key(self) -> Tuple[str, int, Optional[int]]:
        return (self.pattern, self.vocab_size, self.eos_token_id)

    @property
    def n_states(self) -> int:
        return int(self.mask_table.shape[0])

    @property
    def start_state(self) -> int:
        return 0

    @classmethod
    def compile(cls, pattern, tokenizer) -> "GrammarFSM":
        """``pattern`` is a regex string or a JSON-schema dict (lowered
        via :func:`schema_to_regex`); ``tokenizer`` needs
        ``vocab_size``, ``decode_token(id) -> str`` and optionally
        ``eos_token_id`` (:func:`toy_tokenizer` for tests/bench)."""
        if isinstance(pattern, dict):
            pattern = schema_to_regex(pattern)
        dtrans, accepting = _dfa(pattern)
        return cls(pattern, tokenizer, dtrans, accepting)

    # ------------------------------------------------------- host walking
    def next_state(self, state: int, token: int) -> int:
        """State after ``token`` lands, -1 if the token is disallowed
        (never happens for in-step-masked samples)."""
        return int(self.token_next[int(state), int(token)])

    def advance(self, state: int, tokens: Sequence[int]) -> int:
        """Fold :meth:`next_state` over ``tokens`` — how an adoptive
        engine replays a migrated request's journal into its FSM
        state. Raises on a disallowed token: a journal that does not
        walk the grammar is corrupt, not resumable."""
        cur = int(state)
        for t in tokens:
            nxt = self.next_state(cur, t)
            if nxt < 0:
                raise ValueError(
                    f"token {int(t)} disallowed in state {cur} of "
                    f"grammar {self.pattern!r}")
            cur = nxt
        return cur

    def is_accepting(self, state: int) -> bool:
        return int(state) in self._accepting

    def is_complete(self, state: int) -> bool:
        """Accepting with NO continuation token allowed: the structure
        is finished and the host retires the stream with ``"stop"``
        even when the model has no eos token."""
        s = int(state)
        if s not in self._accepting:
            return False
        row = self.mask_table[s].copy()
        if self.eos_token_id is not None:
            row[self.eos_token_id] = False
        return not row.any()

    def allowed(self, state: int) -> np.ndarray:
        """Token ids allowed in ``state`` (eos column included)."""
        return np.nonzero(self.mask_table[int(state)])[0]

    def validates(self, tokens: Sequence[int]) -> bool:
        """True iff ``tokens`` (a finished stream, optional trailing
        eos) walks the grammar start-to-accepting — what chaos/loadgen
        assert on every constrained completion."""
        toks = list(tokens)
        if (self.eos_token_id is not None and toks
                and toks[-1] == self.eos_token_id):
            toks = toks[:-1]
        cur = 0
        for t in toks:
            cur = self.next_state(cur, t)
            if cur < 0:
                return False
        return self.is_accepting(cur)

    def __repr__(self) -> str:
        return (f"GrammarFSM(pattern={self.pattern!r}, "
                f"n_states={self.n_states}, vocab={self.vocab_size})")
