"""Continuous-batching LLM inference engine over the paged KV cache.

The serving counterpart of ``GenerationMixin.generate`` (one static batch,
dense caches): requests join and retire MID-DECODE. The engine keeps a
fixed grid of ``max_batch_slots`` slots and runs ONE **unified ragged
step** for the whole batch — decode slots (one token each) and
mid-prefill slots (a prompt chunk each) ride the same compiled program.
Each engine step

1. **admits** waiting requests into free slots (scheduler.py) in
   (priority, arrival) order under the pool's worst-case page
   accounting — a radix prefix-cache hit (docs/SERVING.md "Prefix
   caching") adopts the cached prefix pages by refcount at admission, so
   chunked prefill starts AFTER the covered prefix,
2. **plans** the step's token mix under a fixed ``token_budget``: decode
   tokens charged first (decode-first under load), prompt chunks sliced
   to fill the remainder in SLO order (priority tier, earliest deadline,
   arrival), then — with ``spec_k > 0`` — speculative draft rows from
   whatever budget is left (``scheduler.plan_drafts``) — a 10k-token
   prompt admits immediately and trickles in without ever displacing a
   decoding tenant's next token,
3. runs the **unified compiled step**: every query token of the step —
   decode tokens, chunk tokens, and draft tokens alike — is one row of a
   flattened
   ``[T, ...]`` grid (ops/pallas/paged_attention.py "Ragged form"), with
   per-row block tables and absolute positions riding as DATA. ``T`` is
   bucketed (the slot grid when the step fits it, powers of two above),
   so XLA compiles a small fixed set of shapes no matter how prompts
   chunk or the live batch churns (asserted via :meth:`compile_counts`
   and ``paddle_tpu_jit_compiles_total{fn="serving_step"}``),
4. **retires** finished sequences (eos or max tokens), freeing their pages
   immediately for the next admission.

Chunked-prefill progress IS a cache length: a slot mid-prompt holds
``pos`` tokens of KV and nothing else — exactly the state a prefix-cache
hit restores, which is why a mid-prefill request migrates at its chunk
boundary like a decoding one (journal = tokens generated so far, possibly
none; the adoptive engine re-prefills what its own cache doesn't cover).

Idle grid rows carry the null block table (all page 0) and a zero
position; their masked garbage rides along and is discarded on the host.
Per-token streaming goes through each request's ``stream_cb`` with a
monotone per-request sequence number.

Determinism contract (docs/SERVING.md "Seeds and determinism"): every
sampled token is keyed ``fold_in(PRNGKey(req.seed), position)`` — the
final chunk's first-token sample and every decode sample derive from the
SAME per-request stream inside the same compiled step, so a request's
tokens are a pure function of (prompt, seed, temperature), independent of
batch composition, chunk boundaries, and engine history. That purity is
what makes in-flight migration exact: :meth:`export_inflight` journals
each live request's generated tokens, and an adopting engine re-prefills
prompt + journal (chunked like any admission) and continues decoding
token-identically from the journaled position.

Telemetry (docs/OBSERVABILITY.md): every step feeds the always-on
``paddle_tpu.metrics`` registry — TTFT / inter-token-latency / queue-wait
/ step-time histograms, the per-step prefill/decode token mix and chunk
sizes, request lifecycle counters, and page/queue gauges (the latter via
``profiler.record_counter``, which ALSO lands them in the chrome trace
next to the ``engine_step`` spans whenever a profiler is recording).
``engine.stats`` stays a thin per-step dict view over the same numbers.
"""
from __future__ import annotations

import inspect
import itertools
import time
import warnings
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults, jit, metrics
from ..autograd.engine import no_grad
from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor
from . import tracing
from .adapters import AdapterStore
from .kv_cache import PagedKVCachePool, PrefixCache
from .scheduler import (BackpressureError, FCFSScheduler, Request,
                        RequestOutput)
from .spec import NGramDrafter

__all__ = ["ServingEngine"]

_MIN_GRID_TOKENS = 16
_engine_counter = itertools.count()

faults.declare_point(
    "serving.step", "top of ServingEngine.step(), before the deadline "
    "sweep — arm latency here to stall whole iterations")
faults.declare_point(
    "serving.prefill", "admission of one request (cache match + page "
    "adoption + slot parking) — a raise retires that request with "
    "finish_reason=\"error\"; batch-mates proceed")
faults.declare_point(
    "serving.decode_step", "in _step_once, after the per-slot KV-room "
    "loop and before the unified compiled step consumes the pools — arm "
    "call= here to corrupt state (e.g. pool.poison_seq), delay_s to trip "
    "the watchdog")
faults.declare_point(
    "serving.compile_step", "building the unified ragged step program — "
    "a transient raise exercises the faults.retry backoff path; each "
    "token-grid bucket still compiles exactly once")


def _cb_accepts_seq(cb) -> bool:
    """True if a stream callback WANTS the 4th positional arg — the
    per-request monotone token sequence number. Signature-probed (the
    MetricsServer health_cb idiom) so the legacy 3-arg
    ``cb(req_id, token, finished)`` contract keeps working unchanged.

    Opting in requires ``*args``, a REQUIRED 4th positional parameter,
    or a parameter named ``seq`` — a legacy callback that merely happens
    to carry a defaulted 4th parameter (``def cb(r, t, f, logger=X)``)
    must NOT suddenly receive an int in it on upgrade."""
    try:
        sig = inspect.signature(cb)
    except (TypeError, ValueError):
        return False
    positional = []
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            return True
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            positional.append(p)
    if len(positional) < 4:
        return False
    fourth = positional[3]
    return fourth.default is fourth.empty or fourth.name == "seq"


class _SeqState:
    """One live slot: request + unified-step cursor.

    The slot's WHOLE generation state is ``(ids, pos, gen)``: ``ids`` is
    the admission token stream (prompt + any migration journal), ``pos``
    counts tokens of KV in the pool — chunked-prefill progress IS a
    cache length — and ``gen`` the tokens sampled here (pre-seeded with
    the journal for a migrated request so stream sequence numbers and
    max_new_tokens accounting continue, not restart). While
    ``pos < len(ids)`` the slot is mid-prefill: each step feeds its next
    prompt chunk ``ids[pos:pos+c]``; the FINAL chunk's sample is the
    stream's next token. Once ``pos == len(ids)`` it decodes:
    ``last_token`` feeds back at position ``pos``.

    No PRNG state lives here: sampling keys are derived per token as
    ``fold_in(PRNGKey(req.seed), position)`` inside the compiled step,
    so (ids, gen) is the WHOLE resume state — exactly what
    :meth:`ServingEngine.export_inflight` ships to a sibling engine on
    migration, chunk boundaries included.
    """

    __slots__ = ("req", "ids", "pos", "last_token", "gen", "t_last",
                 "t_admit", "inserted_nodes", "adp_slot", "fsm",
                 "fsm_off", "fsm_state", "parked")

    def __init__(self, req: Request, ids: np.ndarray, pos: int):
        self.req = req
        self.ids = np.asarray(ids, np.int32).reshape(-1)
        self.pos = int(pos)          # tokens of KV written so far
        self.last_token = -1         # meaningful once prefill completes
        # generated ids (incl. eos when hit); journal-seeded for a
        # migrated request
        self.gen: List[int] = list(req.resume_tokens or ())
        self.t_last = time.perf_counter()  # last token's landing time (ITL)
        self.t_admit = self.t_last   # chunked-prefill wall-time anchor
        # prefix-cache nodes created FROM this request's prefill KV: if a
        # NaN quarantine makes that KV suspect, these (and their
        # subtrees) are evicted so the poison cannot serve a later match
        self.inserted_nodes = []
        # adapter slot in THIS engine's AdapterStore (0 = base model):
        # resolved from req.adapter_id at admission — names travel,
        # slots are engine-local (docs/SERVING.md "Multi-LoRA adapters")
        self.adp_slot = 0
        # constrained decoding (docs/SERVING.md "Constrained decoding"):
        # the request's GrammarFSM, its interned offset in the engine's
        # grammar table, and the LOCAL DFA state advanced per landed
        # token. (fsm_off + fsm_state) is the absolute table row the
        # slot's sample rows gather their logit mask from; fsm_state
        # alone is what export_inflight journals (engine-independent)
        self.fsm = None
        self.fsm_off = 0
        self.fsm_state = 0
        # host-tier park flag (docs/SERVING.md "KV page tiers"): a
        # parked slot keeps its _SeqState (stream position, grammar
        # state, journal) but contributes ZERO rows to the unified step
        # — its KV pages live in the pool's HostPageStore until unpark.
        # False | "auto" (pressure policy; auto-restored) | "manual"
        # (park_request; sticky until unpark_request)
        self.parked = False

    @property
    def prefilling(self) -> bool:
        return self.pos < self.ids.size


class ServingEngine:
    """Continuous-batching engine for any ``GenerationMixin`` model
    (LlamaForCausalLM / GPTForCausalLM): paged KV pool + chunked-prefill
    scheduler + a single unified ragged-paged-attention step (decode
    tokens and prompt chunks in one compiled program).

    ``num_pages=None`` sizes the pool for ``max_batch_slots`` worst-case
    sequences of ``max_model_len`` tokens (+1 null page); pass an explicit
    page count (see docs/SERVING.md for the HBM sizing math) to serve more
    queued requests than fit concurrently — admission simply waits.
    """

    def __init__(self, model, *, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_batch_slots: int = 8,
                 max_model_len: Optional[int] = None,
                 token_budget: int = 1024,
                 prefill_token_budget: Optional[int] = None,
                 min_step_tokens: Optional[int] = None,
                 kv_dtype=jnp.float32, host_offload: bool = False,
                 seed: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 watchdog_stall_s: Optional[float] = 30.0,
                 watchdog_recovery_steps: int = 3,
                 engine_id: Optional[str] = None,
                 model_id: str = "default",
                 prefix_cache: bool = True,
                 spec_k: int = 0, spec_ngram: int = 3,
                 drafter=None,
                 compile_cache_dir: Optional[str] = None,
                 adapter_capacity: int = 4, adapter_rank: int = 4,
                 grammar_states: int = 64):
        if seed is not None:
            # dead since the per-request determinism contract landed:
            # sampling keys derive from fold_in(PRNGKey(req.seed), pos)
            # inside the compiled step, so this arg seeds NOTHING —
            # accepting it silently lets callers believe they pinned
            # reproducibility through a knob that does not exist
            warnings.warn(
                "ServingEngine(seed=...) is deprecated and has no "
                "effect: sampling is keyed per request via "
                "Request.seed (add_request(seed=...)); drop the "
                "constructor argument (docs/SERVING.md \"Seeds and "
                "determinism\")", DeprecationWarning, stacklevel=2)
        self.model = model
        model.eval()
        # identity labels: every per-engine serving series carries
        # {engine_id, model_id} so a Router fronting N engines yields N
        # distinguishable series (docs/OBSERVABILITY.md). The default id is
        # a process-wide counter; a Router assigns stable "model/replica"
        # ids instead.
        self.engine_id = (str(engine_id) if engine_id is not None
                          else str(next(_engine_counter)))
        self.model_id = str(model_id)
        self._lbl = {"engine_id": self.engine_id, "model_id": self.model_id}
        # the fleet-global request tracer (tracing.py): per-request event
        # seqs live THERE, so a migrated request's timeline stays one
        # contiguous stream across engines
        self._trace = tracing.get_tracer()
        self.trunk = model._decode_trunk()
        n_layers, n_kv, head_dim = model._cache_spec()
        self.n_layers = n_layers
        cfg_max = int(model.config.max_position_embeddings)
        self.max_model_len = min(int(max_model_len or cfg_max), cfg_max)
        self.page_size = int(page_size)
        self.max_batch_slots = int(max_batch_slots)
        # prefill_token_budget survives as the PR 1 spelling of the knob;
        # the budget now bounds the WHOLE unified step's tokens (decode
        # charged first, chunks in the remainder — scheduler.plan_chunks)
        self.token_budget = int(prefill_token_budget
                                if prefill_token_budget is not None
                                else token_budget)
        # operator-pinned step-grid floor (docs/SERVING.md "Unified step
        # & chunked prefill"): with min_step_tokens == token_budget every
        # step — decode-only or mixed — compiles and runs ONE shape, so
        # prompt chunks ride rows the decode grid already paid for and
        # the inter-token latency of decoding tenants is isolation-by-
        # construction. None (default) lets decode-only steps use the
        # cheaper slot-grid shape and mixed steps bucket up.
        self.min_step_tokens = (None if min_step_tokens is None
                                else int(min_step_tokens))
        # speculative decoding (docs/SERVING.md "Speculative decoding"):
        # spec_k > 0 arms a host-side drafter that proposes up to k
        # tokens per decoding slot; the unified step scores them as
        # extra grid rows (data, like chunk rows — zero new compiled
        # programs) and the accept/reject below is an exact-match
        # against the per-position sampled targets, so streams are
        # bit-identical with speculation on or off. A custom `drafter`
        # (anything with propose(ids, k) -> np.ndarray) overrides the
        # built-in NGramDrafter.
        self.spec_k = max(int(spec_k), 0)
        if drafter is not None:
            self.drafter = drafter
            self.spec_k = max(self.spec_k, 1)
        elif self.spec_k > 0:
            self.drafter = NGramDrafter(k=self.spec_k,
                                        max_ngram=int(spec_ngram))
        else:
            self.drafter = None
        # sample-grid width: every slot owns spec_k+1 sample rows (base
        # token + drafts); a fixed per-engine constant so the compiled
        # step's signature never varies with how many drafts a given
        # step actually carries
        self._spec_rows = self.spec_k + 1
        self._compile_cache_dir = (None if compile_cache_dir is None
                                   else str(compile_cache_dir))
        # multi-LoRA store (docs/SERVING.md "Multi-LoRA adapters"):
        # ALWAYS built, even when no adapter is ever registered — its
        # stacked (A, B) arrays ride EVERY compiled step as arguments,
        # so registering a tenant later is a pure value write into
        # already-traced shapes (zero recompiles; compile_counts pins
        # it). Slot 0 is the zero-delta identity every base request
        # indexes.
        self.adapters = AdapterStore.from_model(
            model, rank=adapter_rank, capacity=adapter_capacity,
            dtype=jnp.float32)
        # constrained-decoding mask table (docs/SERVING.md "Constrained
        # decoding"): ONE [grammar_states, vocab] boolean table shared
        # by every interned grammar. Row 0 is the all-True identity that
        # unconstrained sample rows point at — jnp.where against it
        # returns the logits bitwise-unchanged, the grammar-off
        # bit-identity guarantee. Grammars intern as refcounted row
        # segments (first-fit); per-slot states ride the step as
        # offset+local ints. Like the adapter arrays, the table is a
        # step ARGUMENT with a fixed shape: interning is a value write.
        self._vocab_size = int(model.config.vocab_size)
        self._grammar_cap = int(grammar_states)
        if self._grammar_cap < 2:
            raise ValueError("grammar_states must be >= 2 (row 0 is the "
                             f"reserved identity), got {grammar_states}")
        self._grammar_table = np.zeros(
            (self._grammar_cap, self._vocab_size), bool)
        self._grammar_table[0, :] = True
        self._grammar_device = jnp.asarray(self._grammar_table)
        # fsm.key -> [offset, n_states, refcount, fsm]
        self._grammar_segments: Dict[object, list] = {}
        self.pages_per_seq = -(-self.max_model_len // self.page_size)
        if num_pages is None:
            num_pages = self.max_batch_slots * self.pages_per_seq + 1
        self.pool = PagedKVCachePool(n_layers, num_pages, self.page_size,
                                     n_kv, head_dim, dtype=kv_dtype,
                                     engine_id=self.engine_id,
                                     model_id=self.model_id)
        # host offload tier (docs/SERVING.md "KV page tiers &
        # quantization"): when armed, admission pressure parks cold
        # lower-urgency slots — their pages swap to the pool's
        # HostPageStore and come back bit-exact at unpark, always BEFORE
        # the slot's next step (the compiled step never blocks on a
        # host→HBM copy; a violation shows up on kv_prefetch_late_total)
        self._host_offload = bool(host_offload)
        # radix prefix cache over the pool (docs/SERVING.md "Prefix
        # caching"): admission longest-prefix-matches cached prompt pages
        # and chunk-prefills only the uncovered suffix. prefix_cache=
        # False opts the whole engine out (every admission prefills from
        # token 0, exactly the pre-cache behavior).
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.pool) if prefix_cache else None)
        self.scheduler = FCFSScheduler(self.max_batch_slots,
                                       self.token_budget,
                                       max_queue=max_queue,
                                       retry_after_cb=self
                                       ._estimate_retry_after)
        # step watchdog (faults.StepWatchdog): trips past the stall
        # threshold, recovers after N healthy steps, drives /healthz via
        # health(). None disables it.
        self.watchdog = (faults.StepWatchdog(
            stall_threshold_s=watchdog_stall_s,
            recovery_steps=watchdog_recovery_steps)
            if watchdog_stall_s is not None else None)
        # EWMA of step wall-time: the drain-rate estimate behind
        # BackpressureError.retry_after_s (seeded at a plausible 50 ms)
        self._avg_step_s = 0.05
        # the ONE shared queue-drain predictor (docs/RESILIENCE.md
        # "Overload & brownout"): backs BOTH the backpressure
        # retry_after_s hint and the overload admission gate, so the
        # hint and the shed decision can never disagree. Imported
        # lazily: overload -> router -> engine would cycle at module
        # import time.
        from .overload import DrainEstimator
        self._estimator = DrainEstimator()
        # OverloadController attached by overload.attach(); None = stock
        # behavior (no admission gate, no brownout actions)
        self._overload = None
        self.slots: List[Optional[_SeqState]] = [None] * self.max_batch_slots
        # THE unified step program: one StaticFunction whose signature
        # cache holds one compiled program per token-grid bucket —
        # decode-only steps, mixed steps, and every chunk geometry reuse
        # the same small set (compile_counts pins it)
        self._step_prog: Optional[jit.StaticFunction] = None
        self._grid_buckets_seen: set = set()
        # NO engine-global RNG: sampling keys derive per slot from
        # fold_in(PRNGKey(req.seed), position) INSIDE the compiled step,
        # so a request's token stream never depends on batch composition
        # or engine history (the `seed` ctor arg survives for API compat
        # but seeds nothing anymore — docs/SERVING.md).
        self._outputs: Dict[object, RequestOutput] = {}
        self.stats: Dict[str, float] = {
            "steps": 0, "generated_tokens": 0, "finished_requests": 0,
            "queue_depth": 0, "running_seqs": 0, "tokens_per_sec": 0.0,
            "page_utilization": 0.0, "peak_pages": 0,
        }
        # typed instruments (docs/OBSERVABILITY.md catalog) — the stats
        # dict above stays a thin per-step view over these. Every series
        # carries {engine_id, model_id}: family-level reads on the
        # registry aggregate across engines, per-engine dashboards filter
        # on the labels.
        reg = metrics.get_registry()
        _eng = ("engine_id", "model_id")
        self._m_ttft = reg.histogram(
            "paddle_tpu_serving_ttft_seconds",
            "Time to first token: request enqueue -> first sampled token",
            labels=_eng).labels(**self._lbl)
        self._m_itl = reg.histogram(
            "paddle_tpu_serving_inter_token_seconds",
            "Inter-token latency: gap between consecutive tokens of one "
            "sequence during decode", labels=_eng).labels(**self._lbl)
        self._m_step = reg.histogram(
            "paddle_tpu_serving_step_seconds",
            "Full engine step: admit + unified ragged step + retire",
            labels=_eng).labels(**self._lbl)
        self._m_prefill = reg.histogram(
            "paddle_tpu_serving_prefill_seconds",
            "One request's whole chunked prefill: admission -> first "
            "sampled token", labels=_eng).labels(**self._lbl)
        self._m_decode = reg.histogram(
            "paddle_tpu_serving_decode_step_seconds",
            "One unified compiled step over all live slots (decode "
            "tokens + prompt chunks)", labels=_eng).labels(**self._lbl)
        self._m_mix = reg.histogram(
            "paddle_tpu_serving_step_mix",
            "Per-step token split of the unified step: tokens of each "
            "kind (decode, prefill chunk, speculative draft) the step "
            "carried", labels=("kind",) + _eng)
        self._m_mix_decode = self._m_mix.labels(kind="decode", **self._lbl)
        self._m_mix_prefill = self._m_mix.labels(kind="prefill",
                                                 **self._lbl)
        self._m_mix_draft = self._m_mix.labels(kind="draft", **self._lbl)
        # speculative-decoding instruments: acceptance is THE health
        # number (accepted/drafted ~ how much free throughput the
        # drafter is buying; near 0 means drafts are wasted grid rows)
        self._m_spec_drafted = reg.counter(
            "paddle_tpu_serving_spec_drafted_tokens_total",
            "Draft tokens proposed by the speculative drafter and scored "
            "as extra unified-step rows", labels=_eng).labels(**self._lbl)
        self._m_spec_accepted = reg.counter(
            "paddle_tpu_serving_spec_accepted_tokens_total",
            "Draft tokens accepted (exact match against the per-position "
            "sampled target); the rest rolled back by KV truncation",
            labels=_eng).labels(**self._lbl)
        self._m_spec_accept = reg.histogram(
            "paddle_tpu_serving_spec_acceptance_ratio",
            "Per-burst acceptance: accepted/drafted for each decode step "
            "that carried draft rows", labels=_eng).labels(**self._lbl)
        self._m_chunk = reg.histogram(
            "paddle_tpu_serving_prefill_chunk_tokens",
            "Tokens per prompt chunk the scheduler sliced under the step "
            "token budget", labels=_eng).labels(**self._lbl)
        self._m_requests = reg.counter(
            "paddle_tpu_serving_requests_total",
            "Requests by lifecycle event",
            labels=("event",) + _eng)
        self._m_tokens = reg.counter(
            "paddle_tpu_serving_generated_tokens_total",
            "Tokens sampled by the engine (prefill first tokens included)",
            labels=_eng).labels(**self._lbl)
        for ev in ("admitted", "rejected", "retired", "preempted"):
            self._m_requests.labels(event=ev, **self._lbl)  # scrapes show 0
        # resilience instruments (docs/RESILIENCE.md): every failure path
        # increments exactly one of these per event, so chaos tests pin
        # telemetry alongside behavior
        self._m_timeouts = reg.counter(
            "paddle_tpu_serving_request_timeouts_total",
            "Admitted requests retired on deadline expiry mid-stream "
            "(finish_reason=\"timeout\"); queued expiry counts "
            "paddle_tpu_serving_expired_total instead",
            labels=_eng).labels(**self._lbl)
        self._m_expired = reg.counter(
            "paddle_tpu_serving_expired_total",
            "QUEUED requests whose deadline lapsed before admission "
            "(finish_reason=\"expired\"): retired with pages never "
            "allocated", labels=_eng).labels(**self._lbl)
        self._m_cancels = reg.counter(
            "paddle_tpu_serving_cancellations_total",
            "Requests retired by cancel() (finish_reason=\"cancelled\")",
            labels=_eng).labels(**self._lbl)
        self._m_nan_quarantines = reg.counter(
            "paddle_tpu_serving_nan_quarantines_total",
            "Sequences quarantined for non-finite decode logits "
            "(finish_reason=\"nan\"); batch-mates are unaffected",
            labels=_eng).labels(**self._lbl)
        self._m_req_errors = reg.counter(
            "paddle_tpu_serving_request_errors_total",
            "Requests retired on an internal failure "
            "(finish_reason=\"error\": admission/alloc/callback faults)",
            labels=_eng).labels(**self._lbl)
        self._m_unavailable = reg.counter(
            "paddle_tpu_serving_unavailable_total",
            "Queued requests retired because no healthy engine could adopt "
            "them (finish_reason=\"unavailable\": the router's "
            "requeue-impossible path)", labels=_eng).labels(**self._lbl)
        self._m_cb_errors = reg.counter(
            "paddle_tpu_serving_callback_errors_total",
            "Exceptions raised by user stream callbacks (isolated: the "
            "engine step survives; the request retires \"error\")",
            labels=_eng).labels(**self._lbl)
        self._m_wd_trips = reg.counter(
            "paddle_tpu_serving_watchdog_trips_total",
            "Watchdog trip episodes (healthy->tripped transitions, not "
            "slow-step count)", labels=_eng).labels(**self._lbl)
        self._m_degraded = reg.gauge(
            "paddle_tpu_serving_degraded",
            "1 while the step watchdog holds this engine degraded "
            "(/healthz returns 503), else 0; refreshed at step end and "
            "on every health() probe", labels=_eng).labels(**self._lbl)
        self._reason_counters = {
            "timeout": self._m_timeouts, "cancelled": self._m_cancels,
            "nan": self._m_nan_quarantines, "error": self._m_req_errors,
            "unavailable": self._m_unavailable,
            "expired": self._m_expired,
        }
        # multi-LoRA + constrained-decoding instruments (ISSUE 16,
        # docs/OBSERVABILITY.md): tenancy split per adapter name, store
        # occupancy, constrained traffic volume, end-of-stream validity
        # (THE constrained-decoding health number: invalid > 0 means a
        # mask or migration bug), spec-draft filtering, and table rows
        self._m_adapter_req = reg.counter(
            "paddle_tpu_serving_adapter_requests_total",
            "Requests admitted under a named LoRA adapter (base/slot-0 "
            "requests are not counted)", labels=("adapter_id",) + _eng)
        self._m_adapter_slots = reg.gauge(
            "paddle_tpu_serving_adapter_slots",
            "Named adapters currently registered in this engine's "
            "AdapterStore (the slot-0 identity is not counted)",
            labels=_eng).labels(**self._lbl)
        self._m_grammar_req = reg.counter(
            "paddle_tpu_serving_grammar_requests_total",
            "Grammar-constrained requests admitted (regex/JSON-schema "
            "FSM attached)", labels=_eng).labels(**self._lbl)
        self._m_grammar_tokens = reg.counter(
            "paddle_tpu_serving_grammar_tokens_total",
            "Tokens landed under an in-step grammar mask (FSM advanced "
            "on the host)", labels=_eng).labels(**self._lbl)
        self._m_grammar_completions = reg.counter(
            "paddle_tpu_serving_grammar_completions_total",
            "Constrained requests retired normally (stop/length) by "
            "whether the finished stream walks its grammar to an "
            "accepting state", labels=("result",) + _eng)
        for r in ("valid", "invalid"):
            self._m_grammar_completions.labels(result=r, **self._lbl)
        self._m_grammar_filtered = reg.counter(
            "paddle_tpu_serving_grammar_draft_filtered_total",
            "Speculative draft tokens dropped before staging because "
            "they would leave the proposer slot's grammar (an unmasked "
            "draft would collapse acceptance)",
            labels=_eng).labels(**self._lbl)
        self._m_grammar_states = reg.gauge(
            "paddle_tpu_serving_grammar_states",
            "Grammar-table rows in use (interned DFA states plus the "
            "row-0 identity) out of the grammar_states capacity",
            labels=_eng).labels(**self._lbl)
        self._m_grammar_states.set(1.0)
        # host-tier SLO guard (docs/OBSERVABILITY.md): pages restored by
        # a BLOCKING prefetch inside _step_once — the unpark policy
        # failed to hide the host→HBM copy before the slot's step
        self._m_prefetch_late = reg.counter(
            "paddle_tpu_serving_kv_prefetch_late_total",
            "KV pages prefetched host→HBM inside the step path (late: "
            "the unpark-time prefetch should have restored them first)",
            labels=_eng).labels(**self._lbl)

    # ------------------------------------------------------------ frontend
    def check_request(self, prompt_len: int, max_new_tokens: int) -> None:
        """Raise ValueError if a request of this shape could NEVER be
        served — batch front doors call this for every prompt before
        queueing any, so one bad prompt can't strand its batch-mates."""
        p, m = int(prompt_len), int(max_new_tokens)
        if p > self.max_model_len:
            # 4xx responses must be actionable: name the violated limit
            # AND its configured value in every rejection message
            self._m_requests.labels(event="rejected", **self._lbl).inc()
            raise ValueError(
                f"prompt_len {p} exceeds the context window (limit: "
                f"max_model_len={self.max_model_len}); truncate the prompt "
                f"or construct the engine with a larger max_model_len")
        total = p + m
        if total > self.max_model_len:
            self._m_requests.labels(event="rejected", **self._lbl).inc()
            raise ValueError(
                f"prompt_len {p} + max_new_tokens {m} = {total} exceeds "
                f"the per-request token cap (limit: max_model_len="
                f"{self.max_model_len}); lower max_new_tokens to at most "
                f"{self.max_model_len - p}")
        need = self.pool.pages_needed(total)
        if need > self.pool.usable_pages:
            # even an empty pool could never admit it — rejecting here
            # (not queueing) keeps run() from spinning forever on a head
            # request that can never pass can_admit
            self._m_requests.labels(event="rejected", **self._lbl).inc()
            raise ValueError(
                f"max_total_tokens {total} needs {need} KV pages "
                f"worst-case but the pool has only {self.pool.usable_pages}"
                f" usable pages (limit: num_pages={self.pool.num_pages}, "
                f"page_size={self.pool.page_size}); raise num_pages or "
                f"lower max_new_tokens")

    def _check_features(self, req: Request) -> None:
        """Adapter/grammar feasibility gate, the :meth:`check_request`
        sibling for the ISSUE 16 features: reject at ENQUEUE anything
        this engine could never serve — an adapter it does not hold, a
        grammar compiled against the wrong vocab, or a DFA larger than
        the grammar table — with the limit named in the message."""
        if (req.adapter_id is not None
                and not self.adapters.holds(req.adapter_id)):
            self._m_requests.labels(event="rejected", **self._lbl).inc()
            raise ValueError(
                f"adapter {req.adapter_id!r} is not registered on this "
                f"engine (holding {list(self.adapters.names())}); "
                f"register it first (Router.register_adapter hot-loads "
                f"fleet-wide) or route via select(adapter_id=...)")
        fsm = req.grammar
        if fsm is not None:
            if int(fsm.vocab_size) != self._vocab_size:
                self._m_requests.labels(event="rejected",
                                        **self._lbl).inc()
                raise ValueError(
                    f"grammar was compiled for vocab_size "
                    f"{int(fsm.vocab_size)} but this model's vocab is "
                    f"{self._vocab_size}; recompile the GrammarFSM "
                    f"against this model's tokenizer")
            if fsm.n_states > self._grammar_cap - 1:
                self._m_requests.labels(event="rejected",
                                        **self._lbl).inc()
                raise ValueError(
                    f"grammar needs {fsm.n_states} DFA states but the "
                    f"table holds at most {self._grammar_cap - 1} "
                    f"(limit: grammar_states={self._grammar_cap}); "
                    f"simplify the pattern or raise grammar_states")

    def add_request(self, prompt, max_new_tokens: int = 32,
                    temperature: float = 0.0,
                    eos_token_id: Optional[int] = None, seed: int = 0,
                    stream_cb=None, deadline_s: Optional[float] = None,
                    prefix_cache: bool = True, priority: int = 0,
                    adapter_id: Optional[str] = None, grammar=None):
        """Queue a request; returns its ``req_id``. Generation starts at
        the next :meth:`step` with capacity (continuous batching — no
        barrier on the current batch). ``deadline_s`` bounds the whole
        request from ENQUEUE (queue wait included): past it, the engine
        retires it with ``finish_reason="timeout"``. Raises
        :class:`~.scheduler.BackpressureError` (with a ``retry_after_s``
        hint) when a bounded queue (``max_queue=``) is full.
        ``prefix_cache=False`` opts THIS request out of prefix-cache
        matching and insertion (it prefills from token 0 and shares no
        pages) — the per-request escape hatch next to the engine-level
        ``prefix_cache=`` constructor flag. ``priority`` is the SLO tier
        (lower = more urgent, 0 default): honored at admission order and
        at prompt-chunk scheduling (docs/SERVING.md "Unified step &
        chunked prefill"). ``adapter_id`` names a LoRA adapter this
        engine must already hold (``register_adapter``); ``grammar`` is
        a compiled :class:`~.grammar.GrammarFSM` constraining every
        sampled token (docs/SERVING.md "Constrained decoding")."""
        req = Request(prompt=np.asarray(prompt, np.int32).reshape(-1),
                      max_new_tokens=max_new_tokens, temperature=temperature,
                      eos_token_id=eos_token_id, seed=seed,
                      stream_cb=stream_cb, deadline_s=deadline_s,
                      prefix_cache=prefix_cache, priority=priority,
                      adapter_id=adapter_id, grammar=grammar)
        self.check_request(req.prompt.size, req.max_new_tokens)
        self._check_features(req)
        if self._overload is not None:
            # deadline-aware admission (docs/RESILIENCE.md "Overload &
            # brownout"): shed doomed work BEFORE it enters the queue.
            # Only fresh submits are gated — adopt_request (failover of
            # already-accepted work) bypasses on purpose.
            try:
                self._overload.admission_check(self, req)
            except BackpressureError:
                self._m_requests.labels(event="rejected",
                                        **self._lbl).inc()
                raise
        try:
            self.scheduler.add(req)
        except Exception:
            self._m_requests.labels(event="rejected", **self._lbl).inc()
            raise
        self._trace.emit("req.enqueue", req.req_id,
                         arg=float(req.prompt.size), label=self.engine_id)
        return req.req_id

    def cancel(self, req_id) -> bool:
        """Cancel a request wherever it is: pulled from the queue, or
        retired mid-prefill/mid-decode with its KV pages freed THIS
        call. The output (tokens generated so far,
        ``finish_reason="cancelled"``) is delivered through the usual
        :meth:`run` path and the terminal stream callback fires. False
        if the request is unknown or already finished — cancel is
        idempotent, never raises."""
        req = self.scheduler.remove(req_id)
        if req is not None:
            self._finish_queued(req, "cancelled")
            return True
        for i, st in enumerate(self.slots):
            if st is not None and st.req.req_id == req_id:
                self._retire_abnormal(st, slot=i, reason="cancelled")
                return True
        return False

    def health(self) -> Dict[str, object]:
        """Liveness view for ``MetricsServer(health_cb=engine.health)``:
        ``status`` flips to ``"degraded"`` while the watchdog is tripped
        OR a step is live-hung past the stall threshold (stalled_now is
        answerable from the scrape thread mid-step)."""
        degraded = (self.watchdog is not None
                    and self.watchdog.status() != "ok")
        # keep the gauge agreeing with /healthz even MID-step: a live
        # hang is only observable from this (scrape) thread, and the
        # step's own finally can't run until the hang ends
        self._m_degraded.set(1.0 if degraded else 0.0)
        return {
            "status": "degraded" if degraded else "ok",
            "watchdog_trips": (0 if self.watchdog is None
                               else self.watchdog.trips),
            "queue_depth": self.scheduler.queue_depth,
            "running_seqs": sum(1 for s in self.slots if s is not None),
        }

    def _estimate_retry_after(self) -> float:
        """Backpressure hint: admission drains roughly one request per
        step per free slot, so a full queue clears in about
        ``queue_depth x avg_step_time`` — rounded up to a 50 ms floor so
        clients never busy-spin on a hot engine. Delegates to the ONE
        shared :class:`~.overload.DrainEstimator` so this hint and the
        overload admission gate agree by construction."""
        return self._estimator.for_engine(self)

    @property
    def has_work(self) -> bool:
        return bool(self.scheduler.waiting) or any(
            s is not None for s in self.slots)

    def run(self) -> Dict[object, RequestOutput]:
        """Drive :meth:`step` until queue and slots drain; returns every
        request finished since the last :meth:`run` (including ones that
        retired in explicit :meth:`step` calls in between), keyed by
        ``req_id``. Draining — outputs are handed out exactly once, so a
        long-lived server never accumulates them."""
        while self.has_work:
            self.step()
        return self.take_outputs()

    def take_outputs(self) -> Dict[object, RequestOutput]:
        """Drain accumulated terminal outputs WITHOUT stepping (exactly-once
        handout, same contract as :meth:`run`). The router's collection
        path: it steps many engines itself and merges their outputs."""
        out, self._outputs = self._outputs, {}
        return out

    # ------------------------------------------------- router control plane
    def steal_queued(self) -> List[Request]:
        """Pull EVERY waiting (never-admitted) request out of the queue and
        return the live Request objects — the router's drain/failover path.
        No lifecycle counters move: the requests were never admitted here
        and are about to be adopted elsewhere (or retired explicitly via
        :meth:`retire_queued`). In-flight slots are untouched; they finish
        or fall to the cancel/deadline machinery."""
        return self.scheduler.pop_all()

    def export_inflight(self) -> List[Request]:
        """Pop every IN-FLIGHT request (decode slots AND mid-chunked-
        prefill slots) off this engine and return resume journals: each
        Request comes back with ``resume_tokens`` set to the tokens it
        generated here — together with (prompt, seed, temperature,
        deadline, priority) already on the Request, the complete state a
        sibling needs to continue the stream token-identically (chunked
        re-prefill of prompt + journal, then decode from the journaled
        position; emission resumes at stream seq ``len(resume_tokens)``).
        A slot killed BETWEEN prompt chunks journals exactly its tokens
        so far (usually none): its chunk progress was only a cache
        length, which the adoptive engine's prefix cache re-covers — so
        migration at a chunk boundary is the same move as migration
        mid-decode. A CONSTRAINED request additionally journals its DFA
        position in ``resume_fsm_state`` (the engine-independent LOCAL
        state — table offsets differ per engine), so the sibling resumes
        mid-structure without re-walking the grammar. The router's
        migration path for ``mark_down``/step-crash.

        No lifecycle counters move (the requests retire elsewhere), and
        pages are freed best-effort per sequence — a crashed engine's
        pool may refuse, and its memory is being abandoned anyway."""
        states: List[_SeqState] = []
        for i, st in enumerate(self.slots):
            if st is not None:
                states.append(st)
                self.slots[i] = None
        out: List[Request] = []
        for st in states:
            try:
                if self.pool.has_seq(st.req.req_id):
                    self.pool.free(st.req.req_id)
            except Exception:
                pass  # dead pool: journaling must still succeed
            st.req.resume_tokens = list(st.gen)
            if st.fsm is not None:
                st.req.resume_fsm_state = st.fsm_state
            self._grammar_release(st)
            self._trace.emit("req.export", st.req.req_id,
                             arg=float(len(st.req.resume_tokens)),
                             label=self.engine_id)
            out.append(st.req)
        return out

    def inflight_fsm_states(self) -> Dict[object, Optional[int]]:
        """``{req_id: local grammar FSM state}`` for every live slot
        (None for unconstrained requests) — a read-only snapshot, slots
        untouched. What the router's WAL group commit journals next to
        each progress record so a restarted process can resume a
        constrained stream mid-structure without re-walking the DFA
        (a missing journaled state is recomputed from the token journal
        at adoption, exactly like a migrated request's)."""
        out: Dict[object, Optional[int]] = {}
        for st in self.slots:
            if st is not None:
                out[st.req.req_id] = (int(st.fsm_state)
                                      if st.fsm is not None else None)
        return out

    def adopt_request(self, req: Request) -> None:
        """Enqueue a Request object stolen from ANOTHER engine: req_id,
        arrival time, running deadline, seed, and stream_cb all ride along,
        so queue-wait/TTFT keep measuring from the original enqueue and the
        caller's streaming keeps working. A request journaled by
        :meth:`export_inflight` (``resume_tokens`` set) re-prefills
        prompt + journal at admission (in chunks, like any admission) and
        continues its stream token-identically. Raises exactly like
        :meth:`add_request` (ValueError from :meth:`check_request` or
        :meth:`_check_features` — an adapter this engine doesn't hold is
        a placement error, BackpressureError from a full bounded queue)
        — the router treats a raise as requeue-impossible."""
        self.check_request(req.prompt.size, req.max_new_tokens)
        self._check_features(req)
        try:
            self.scheduler.add(req)
        except Exception:
            self._m_requests.labels(event="rejected", **self._lbl).inc()
            raise
        self._trace.emit("req.adopt", req.req_id,
                         arg=float(len(req.resume_tokens or ())),
                         label=self.engine_id)

    def retire_queued(self, req: Request,
                      reason: str = "unavailable") -> RequestOutput:
        """Terminally retire a request that is NOT queued here anymore
        (stolen via :meth:`steal_queued`) and could not be placed on any
        healthy engine: emits the terminal stream callback and the
        per-reason counter, and delivers the output through this engine's
        normal :meth:`run`/:meth:`take_outputs` path — exactly once, like
        every other retirement."""
        return self._finish_queued(req, reason)

    # ------------------------------------------------ adapters and grammars
    def register_adapter(self, name: str, weights) -> int:
        """Install (or hot-swap) LoRA adapter ``name`` on THIS engine —
        a pure value write into the stacked adapter arrays, so the
        compiled step is untouched (``compile_counts()`` before == after)
        and in-flight work never notices. Fleet-wide hot-load goes
        through ``Router.register_adapter``, which adds the canary."""
        slot = self.adapters.register(name, weights)
        self._m_adapter_slots.set(float(len(self.adapters.names())))
        return slot

    def unregister_adapter(self, name: str) -> None:
        """Zero and free adapter ``name``'s slot. Refuses while any
        admitted OR queued request still points at it — unregistering
        under a live tenant would silently flip its deltas to zero
        mid-stream."""
        if self._adapter_in_use(name):
            raise ValueError(
                f"adapter {name!r} is in use by an admitted or queued "
                f"request; drain it before unregistering")
        self.adapters.unregister(name)
        self._m_adapter_slots.set(float(len(self.adapters.names())))

    def _adapter_in_use(self, name: str) -> bool:
        for st in self.slots:
            if st is not None and st.req.adapter_id == name:
                return True
        return any(r.adapter_id == name for r in self.scheduler.waiting)

    def _grammar_intern(self, fsm) -> int:
        """Refcounted first-fit interning of a compiled DFA into the ONE
        ``[grammar_states, vocab]`` device table the step consumes:
        returns the row offset for this grammar. Same ``fsm.key`` →
        same rows (a popular schema costs its states once, not per
        request). Row 0 is the reserved all-True identity."""
        seg = self._grammar_segments.get(fsm.key)
        if seg is not None:
            seg[2] += 1
            return seg[0]
        n = int(fsm.n_states)
        taken = sorted((s[0], s[1]) for s in self._grammar_segments.values())
        off, ok = 1, False
        for seg_off, seg_n in taken:
            if off + n <= seg_off:
                ok = True
                break
            off = seg_off + seg_n
        if not ok and off + n > self._grammar_cap:
            held = {str(k[0]): s[1] for k, s in
                    self._grammar_segments.items()}
            raise ValueError(
                f"grammar table full: need {n} rows but only "
                f"{self._grammar_cap - off} remain of "
                f"grammar_states={self._grammar_cap} (holding {held}); "
                f"raise grammar_states or drain constrained requests")
        self._grammar_table[off:off + n] = fsm.mask_table
        self._grammar_device = jnp.asarray(self._grammar_table)
        self._grammar_segments[fsm.key] = [off, n, 1, fsm]
        self._m_grammar_states.set(float(1 + sum(
            s[1] for s in self._grammar_segments.values())))
        return off

    def _grammar_release(self, st: "_SeqState") -> None:
        """Drop ``st``'s reference on its interned grammar; at refcount
        zero the rows are zeroed and the segment freed. Idempotent —
        every retirement path calls it unconditionally."""
        fsm, st.fsm = st.fsm, None
        if fsm is None:
            return
        seg = self._grammar_segments.get(fsm.key)
        if seg is None:
            return
        seg[2] -= 1
        if seg[2] <= 0:
            off, n = seg[0], seg[1]
            self._grammar_table[off:off + n] = False
            self._grammar_device = jnp.asarray(self._grammar_table)
            del self._grammar_segments[fsm.key]
        self._m_grammar_states.set(float(1 + sum(
            s[1] for s in self._grammar_segments.values())))

    @property
    def avg_step_s(self) -> float:
        """Step wall-time EWMA — the same drain-rate estimate behind
        ``BackpressureError.retry_after_s``, exposed for the router's
        least-loaded scoring."""
        return self._avg_step_s

    def load_score(self) -> float:
        """Estimated seconds to drain this engine's current commitment:
        outstanding work in STEPS x the step-time EWMA. A slot's charge
        is its remaining prompt in CHUNK steps (ceil(remaining /
        token_budget) — chunked-prefill progress counts: a 10k prompt
        90% prefilled weighs a tenth of a fresh one) plus one decode
        step per remaining token (a 2-token short and a 128-token hog
        must not weigh the same). The queue half rides the scheduler's
        incremental tally (O(1)); the slot scan is bounded by
        ``max_batch_slots``. The router's least-loaded dispatch admits
        onto the minimum-score healthy engine; exact ties (idle fleets)
        round-robin."""
        budget = max(self.scheduler.token_budget, 1)
        steps = self.scheduler.pending_steps
        for st in self.slots:
            if st is None:
                continue
            remaining_prefill = max(int(st.ids.size) - st.pos, 0)
            steps += -(-remaining_prefill // budget)
            steps += max(int(st.req.max_new_tokens) - len(st.gen), 0)
        return steps * self._avg_step_s

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program tally — the recompilation bound the tests
        assert on: ONE unified step function whose compiled signatures
        are exactly the token-grid buckets seen, so ``step`` must equal
        ``step_buckets`` forever (a drift means something non-bucketed —
        a dtype, a shape — leaked into the program signature) and both
        are bounded by the small fixed bucket set."""
        n = len(self._step_prog._cache) if self._step_prog else 0
        return {"step": n, "step_buckets": len(self._grid_buckets_seen)}

    # ---------------------------------------------------------------- step
    def step(self) -> List[RequestOutput]:
        """One engine iteration: admit → one unified ragged step (decode
        tokens + prompt chunks under the token budget) → retire. Returns
        requests that finished during this step."""
        from ..profiler import RecordEvent, record_counter

        t0 = time.perf_counter()
        if self.watchdog is not None:
            self.watchdog.begin_step()
        tokens_before = self.stats["generated_tokens"]
        finished: List[RequestOutput] = []
        try:
            faults.point("serving.step")
            with RecordEvent("engine_step"):
                finished.extend(self._sweep_deadlines())
                if self._overload is not None:
                    # brownout level >= 3: preempt batch-tier decode
                    # slots (journal + requeue, the migration move
                    # turned inward) — BEFORE admission so the freed
                    # slots and pages are available to interactive
                    # work this very step
                    self._brownout_enforce()
                if self._host_offload:
                    # page pressure relief BEFORE admission: parking a
                    # cold low-priority slot moves its pages (and its
                    # worst-case tail reservation) to the host tier, so
                    # can_admit sees the reclaimed capacity this very
                    # step — offload-before-reject, and before the
                    # prefix cache gets evicted for the same pages
                    self._park_for_pressure()
                free = sum(1 for s in self.slots if s is None)
                _cap = (None if self._overload is None
                        else self._overload.admit_priority_cap())
                for req in self.scheduler.admit(free, self.pool,
                                                max_priority=_cap):
                    self._m_requests.labels(event="admitted", **self._lbl).inc()
                    try:
                        # an admission failure (cache/alloc fault,
                        # injected drill) fails THIS request, not the
                        # engine: batch-mates keep decoding, the queue
                        # keeps draining
                        self._admit(req)
                    except Exception as e:
                        finished.append(
                            self._fail_admitted_request(req, e))
                if self._host_offload:
                    # restore parked slots whose pages now fit again —
                    # AFTER admission so a just-admitted head request is
                    # never displaced by the stream it preempted
                    self._unpark_ready()
                if any(s is not None for s in self.slots):
                    finished.extend(self._step_once())
        finally:
            # the watchdog bracket must close even when the step body
            # raises (an armed fault, an unhandled bug) — otherwise
            # _in_step_since stays set and an IDLE engine reads as
            # live-hung on /healthz forever
            dt = time.perf_counter() - t0
            self._avg_step_s = 0.8 * self._avg_step_s + 0.2 * dt
            if self.watchdog is not None:
                if self.watchdog.end_step(dt):
                    self._m_wd_trips.inc()
                self._m_degraded.set(
                    0.0 if self.watchdog.status() == "ok" else 1.0)
        self._m_step.observe(dt)
        self.stats["steps"] += 1
        self.stats["queue_depth"] = self.scheduler.queue_depth
        self.stats["running_seqs"] = sum(
            1 for s in self.slots if s is not None)
        # zero-duration guard: a clock with coarse resolution can report
        # dt == 0 for an idle step — a rate of 0 beats a ZeroDivisionError
        # (or the absurd spike 1e-9 used to produce)
        tokens_this_step = self.stats["generated_tokens"] - tokens_before
        self.stats["tokens_per_sec"] = (
            tokens_this_step / dt if dt > 0.0 else 0.0)
        self.stats["page_utilization"] = self.pool.utilization()
        self.stats["peak_pages"] = self.pool.peak_used
        record_counter("serving.queue_depth", self.stats["queue_depth"])
        record_counter("serving.running_seqs", self.stats["running_seqs"])
        record_counter("serving.tokens_per_sec",
                       self.stats["tokens_per_sec"])
        record_counter("serving.page_utilization",
                       self.stats["page_utilization"])
        # engine-scoped trace event: step.tokens keys on the engine_id,
        # so trace_dump renders engine throughput as a counter track
        # next to the per-request tracks
        self._trace.emit("step.tokens", self.engine_id,
                         arg=float(tokens_this_step))
        # outputs were registered in self._outputs eagerly at retirement
        return finished

    # ------------------------------------------------- host-tier parking
    def _find_slot(self, req_id):
        for i, st in enumerate(self.slots):
            if st is not None and st.req.req_id == req_id:
                return i, st
        raise KeyError(f"unknown or finished request: {req_id!r}")

    def park_request(self, req_id) -> int:
        """Park a live request: its exclusively-owned KV pages swap to
        the pool's host tier, its unwritten-tail reservation is released,
        and the slot contributes ZERO rows to the unified step until
        :meth:`unpark_request`. The slot itself stays occupied — parking
        frees PAGES, not slots — and the whole stream state (position,
        grammar DFA, journal) survives in place. Returns pages moved;
        idempotent on an already-parked request.

        A park requested through THIS public API is sticky: the per-step
        pressure policy never auto-unparks it (an external controller
        parked it for reasons the engine cannot see); only pressure
        parks (``_park_for_pressure``) auto-restore via
        ``_unpark_ready``."""
        return self._park(req_id, mode="manual")

    def _park(self, req_id, mode: str) -> int:
        if not self._host_offload:
            raise RuntimeError(
                "host_offload is disabled on this engine "
                "(ServingEngine(host_offload=True) to enable the tier)")
        _, st = self._find_slot(req_id)
        if st.parked:
            return 0
        n = self.pool.offload_seq(req_id)
        st.parked = mode
        self._trace.emit("req.park", req_id, arg=float(n))
        return n

    def unpark_request(self, req_id) -> int:
        """Restore a parked request's offloaded pages into HBM (bit-exact
        — bytes and int8 scales scattered back verbatim) and re-assume
        its tail reservation; the slot rejoins the next step's grid.
        Raises if the pool cannot cover the restore — callers gate on
        ``pool.can_prefetch``. Returns pages restored."""
        if not self._host_offload:
            raise RuntimeError(
                "host_offload is disabled on this engine "
                "(ServingEngine(host_offload=True) to enable the tier)")
        _, st = self._find_slot(req_id)
        if not st.parked:
            return 0
        n = self.pool.prefetch_seq(req_id)
        st.parked = False
        self._trace.emit("req.unpark", req_id, arg=float(n))
        return n

    def _park_for_pressure(self) -> None:
        """Offload-before-reject: when the queue head cannot admit for
        PAGES while a decode slot sits free, park the coldest strictly
        lower-priority streams until the head's worst case fits. Runs
        before admission each step; victims keep their slots (their
        pages and tail reservations are what the head needs), so this
        only helps when slots outnumber page capacity — exactly the
        overcommitted sizing the host tier exists for."""
        sched = self.scheduler
        if not sched.waiting:
            return
        if not any(s is None for s in self.slots):
            return  # no free slot: parking frees pages, not slots
        head = sched.waiting[0]
        matched = (self.pool.prefix_match_len(head.admission_ids())
                   if head.prefix_cache else 0)
        cached = matched // self.page_size
        if self.pool.can_admit(head.max_total_tokens, cached_pages=cached):
            return
        cands = [(st.t_last, st.req.req_id, st.req)
                 for st in self.slots
                 if st is not None and not st.parked and not st.prefilling]
        for rid in sched.offload_victims(head, cands):
            self._park(rid, mode="auto")
            if self.pool.can_admit(head.max_total_tokens,
                                   cached_pages=cached):
                return

    def _brownout_enforce(self) -> None:
        """Brownout ladder level >= 3 (``batch-parked``): preempt every
        live batch-tier decode slot — journal its generated tokens onto
        the Request (:meth:`export_inflight`'s move, turned inward),
        free its pages AND its slot, and requeue it behind higher
        tiers. Host-tier parking keeps the slot (it frees pages only),
        which is exactly wrong when slots are the scarce resource under
        overload; the journal costs a chunked re-prefill on restore —
        which the prefix cache largely covers — and buys a whole slot.

        Restoration is ordinary admission: the requeued request carries
        ``resume_tokens``, the ladder's admission hold (level >= 3
        holds the batch tier; see ``FCFSScheduler.admit``) keeps it
        queued until de-escalation, and the resumed stream is
        token-identical (sampling is keyed on (seed, position), never
        on the slot) — the same contract migration already proves.
        A preemption that would overflow the bounded queue is skipped:
        a stream is never dropped to make room for one.

        The victim set widens with the ladder
        (``OverloadController.preempt_priority_cut``): ``batch-parked``
        evicts the batch tier; ``interactive-only`` evicts every
        non-interactive tier."""
        cut = self._overload.preempt_priority_cut()
        if cut is None:
            return
        sched = self.scheduler
        for i, st in enumerate(self.slots):
            if (st is None or st.parked or st.prefilling
                    or st.req.priority < cut):
                continue
            if (sched.max_queue is not None
                    and len(sched.waiting) >= sched.max_queue):
                return
            self.slots[i] = None
            try:
                if self.pool.has_seq(st.req.req_id):
                    self.pool.free(st.req.req_id)
            except Exception:
                pass  # pool fault: the journal must still requeue
            st.req.resume_tokens = list(st.gen)
            if st.fsm is not None:
                st.req.resume_fsm_state = st.fsm_state
            self._grammar_release(st)
            self._trace.emit("req.preempt", st.req.req_id,
                             arg=float(len(st.req.resume_tokens)),
                             label=self.engine_id)
            self._m_requests.labels(event="preempted", **self._lbl).inc()
            sched.add(st.req)

    def _unpark_ready(self) -> None:
        """Restore parked tenants whose pages fit again, highest
        priority / oldest first. Anti-thrash: when the queue still has a
        head, an unpark must leave that head's worst case admittable —
        otherwise the next step would park the same slot right back.
        Manual parks never auto-restore."""
        parked = [(st.req.priority, st.req.arrival_t, st.req.req_id)
                  for st in self.slots
                  if st is not None and st.parked == "auto"]
        if not parked:
            return
        head_need = 0
        if self.scheduler.waiting:
            head = self.scheduler.waiting[0]
            matched = (self.pool.prefix_match_len(head.admission_ids())
                       if head.prefix_cache else 0)
            head_need = max(
                self.pool.pages_needed(head.max_total_tokens)
                - matched // self.page_size, 0)
        for _, _, rid in sorted(parked):
            if not self.pool.can_prefetch(rid):
                continue
            if (head_need and self.pool.spare_pages()
                    - self.pool.prefetch_cost(rid) < head_need):
                continue
            self.unpark_request(rid)

    # -------------------------------------------------- resilience helpers
    def _compile_with_retry(self, point_name: str, make_fn):
        """Build a compiled program under a fault point with a short
        seeded backoff (ONE retry policy for every build site): a
        transient failure costs milliseconds, a persistent one surfaces
        to step()'s per-request isolation. The program still compiles
        exactly once per bucket — only the successful build reaches
        XLA."""
        def build():
            faults.point(point_name)
            return make_fn()

        return faults.retry(build, attempts=3, base_delay_s=0.01,
                            max_delay_s=0.1)

    def _safe_cb(self, req: Request, token, finished, seq: int):
        """Invoke ``req.stream_cb`` isolated: a raising user callback
        cannot abort :meth:`step`. Records the error, disables the
        callback (no further calls for this request), and returns the
        exception (None on success) so the caller can retire the
        request with ``"error"`` carrying the diagnostic.

        ``seq`` is the request's monotone token sequence number (0-based
        generated index; the terminal call passes the total emitted
        count). A callback whose signature takes a 4th positional arg
        receives it — the exactly-once streaming cursor: a migrated
        request's adoptive engine resumes emission at the journaled seq,
        so a client never sees a duplicated or missing chunk. Legacy
        3-arg callbacks are called exactly as before."""
        cb = req.stream_cb
        wants_seq = getattr(req, "_cb_wants_seq", None)
        if wants_seq is None:
            wants_seq = _cb_accepts_seq(cb)
            req._cb_wants_seq = wants_seq  # probe once, rides with req
        try:
            if wants_seq:
                cb(req.req_id, token, finished, seq)
            else:
                cb(req.req_id, token, finished)
            return None
        except Exception as e:
            self._m_cb_errors.inc()
            req.stream_cb = None
            return e

    def _emit_terminal(self, req: Request, gen, reason: str,
                       error=None) -> RequestOutput:
        """Common tail of every abnormal retirement: per-reason counter
        (exactly once per event), lifecycle counter, terminal stream
        callback (isolated), RequestOutput."""
        self._reason_counters[reason].inc()
        self._trace.emit("req.retire", req.req_id, label=reason)
        self._m_requests.labels(event="retired", **self._lbl).inc()
        self.stats["finished_requests"] += 1
        out = RequestOutput(req_id=req.req_id, prompt_token_ids=req.prompt,
                            token_ids=list(gen), finish_reason=reason,
                            error=None if error is None else repr(error))
        # register EAGERLY: if the rest of this step raises (an armed
        # fault, a bug), run() must still deliver every output whose
        # retirement side effects (pages freed, counters, terminal
        # callback) already happened
        self._outputs[out.req_id] = out
        if req.stream_cb is not None:
            self._safe_cb(req, None, reason, len(out.token_ids))
        return out

    def _finish_queued(self, req: Request, reason: str) -> RequestOutput:
        """Retire a request that never ran HERE (timeout/cancel/
        unavailable in queue). A migrated request carries its journal:
        the tokens it generated before its engine died are delivered —
        they were already streamed, so the output must own them too."""
        return self._emit_terminal(req, list(req.resume_tokens or ()),
                                   reason)

    def _fail_admitted_request(self, req: Request,
                               error: Exception) -> RequestOutput:
        """Retire a request whose admission failed partway; any pages its
        allocation grabbed go back to the pool now. A migrated request's
        journaled tokens still deliver — they were already streamed."""
        if self.pool.has_seq(req.req_id):
            self.pool.free(req.req_id)
        return self._emit_terminal(req, list(req.resume_tokens or ()),
                                   "error", error)

    def _retire_abnormal(self, st: _SeqState, slot: int,
                         reason: str, error=None) -> RequestOutput:
        """Retire a LIVE sequence off the normal eos/length path
        (timeout / cancelled / nan / error): pages freed this call, slot
        cleared, tokens generated so far delivered."""
        req = st.req
        if reason == "nan" and st.inserted_nodes and \
                self.prefix_cache is not None:
            # prefix nodes built FROM this request's (now suspect) KV
            # must never serve another admission: evict them and any
            # subtree grown on top; pages pinned by live sequences stay
            # until those retire, and the release is scrub-marked
            self.prefix_cache.evict_nodes(st.inserted_nodes)
        if self.pool.has_seq(req.req_id):
            # scrub=True for NaN: the pool zeroes each freed page lazily
            # on reuse — attention masks give padding lanes weight 0,
            # but IEEE 0 * NaN = NaN, so a poisoned page handed to the
            # next sequence would re-poison it through its masked tail.
            # Normal retires skip it: finite garbage IS annihilated by
            # the 0 weights. Pages a sibling or the cache still
            # references defer (scrub-pending, zeroed at refcount zero).
            self.pool.free(req.req_id, scrub=(reason == "nan"))
        self.slots[slot] = None
        self._grammar_release(st)
        return self._emit_terminal(req, st.gen, reason, error)

    def _sweep_deadlines(self) -> List[RequestOutput]:
        """Retire every over-deadline request; runs at the top of each
        step so an overloaded queue sheds load instead of serving stale
        work. Still-QUEUED requests retire ``finish_reason="expired"``
        — their deadline lapsed while waiting, pages never allocated —
        while admitted (mid-prefill / mid-decode) requests retire
        ``"timeout"`` with the tokens generated so far. The split keeps
        the overload story honest: ``expired`` counts work the fleet
        never touched, ``timeout`` counts work it started but could not
        finish in time. A queued request carrying a journal (migrated
        or brownout-preempted — the fleet DID touch it) therefore
        retires ``"timeout"``, keeping ``expired`` an exact count of
        never-admitted work."""
        finished: List[RequestOutput] = []
        for req in self.scheduler.pop_expired():
            if req.resume_tokens is not None:
                finished.append(self._finish_queued(req, "timeout"))
                continue
            self._trace.emit("req.expire", req.req_id,
                             label=self.engine_id)
            finished.append(self._finish_queued(req, "expired"))
        for i, st in enumerate(self.slots):
            if (st is not None and st.req.deadline is not None
                    and st.req.deadline.expired()):
                finished.append(
                    self._retire_abnormal(st, slot=i, reason="timeout"))
        return finished

    # ----------------------------------------------------------- admission
    def _admit(self, req: Request) -> None:
        """Park a request in a free slot: longest-prefix match against
        the radix cache (full pages, capped at s-1 so the final chunk
        always computes the first sample's logits), adopt matched pages
        by refcount, and set the chunk cursor. The prefill itself runs
        inside the next unified steps, sliced under the token budget —
        admission costs no model compute at all. A migrated request
        (``resume_tokens`` set) admits over prompt + journal: chunked
        re-prefill rebuilds the KV the dead engine held, and the final
        chunk's sample IS the stream's next token (docs/RESILIENCE.md
        "In-flight migration"). Admission also binds ISSUE 16's tenancy
        data: the request's adapter slot index, and its interned grammar
        (offset + DFA state — seeded from ``resume_fsm_state`` for a
        migrated request, else by walking the journal, so constrained
        streams resume mid-structure)."""
        faults.point("serving.prefill")
        ids = req.admission_ids()
        cache = self.prefix_cache if req.prefix_cache else None
        if cache is not None:
            matched, shared_pages, _nodes = cache.match(ids)
        else:
            matched, shared_pages = 0, []
        # matched pages join the table by refcount (no free-list draw,
        # bumped before any fresh page is taken so eviction can't race
        # the adoption); the chunk cursor starts AFTER the covered
        # prefix — chunked-prefill progress and cache hits are the same
        # thing, a cache length
        self.pool.allocate(req.req_id, matched,
                           max_total_tokens=req.max_total_tokens,
                           prefix_pages=shared_pages,
                           prefix_tokens=matched)
        st = _SeqState(req, ids, pos=matched)
        try:
            st.adp_slot = self.adapters.slot(req.adapter_id)
        except KeyError as e:
            raise ValueError(str(e))
        if req.adapter_id is not None:
            self._m_adapter_req.labels(adapter_id=req.adapter_id,
                                       **self._lbl).inc()
        if req.grammar is not None:
            st.fsm_off = self._grammar_intern(req.grammar)
            st.fsm = req.grammar
            if req.resume_fsm_state is not None:
                st.fsm_state = int(req.resume_fsm_state)
            else:
                # fresh admission: the journal (if any) was generated
                # under this same grammar — walk it to the live state
                st.fsm_state = st.fsm.advance(0, req.resume_tokens or ())
            self._m_grammar_req.inc()
        self.slots[self.slots.index(None)] = st
        self._trace.emit("req.admit", req.req_id, arg=float(matched),
                         label=self.engine_id)
        if matched:
            self._trace.emit("req.prefix_hit", req.req_id,
                             arg=float(matched))

    # --------------------------------------------------- unified step
    def _grid_tokens(self, total: int) -> int:
        """Token-grid bucket for one unified step: the slot grid B while
        the step fits it (a decode-only step costs exactly what the old
        decode-only program did, and a small chunk rides padding rows
        that grid already pays for), else the next power of two (floored
        at 16) — with an optional operator-pinned floor
        (``min_step_tokens``) that freezes EVERY step to one shape, the
        strongest inter-token-latency isolation: prompt chunks can never
        change the compiled step's cost (docs/SERVING.md "Unified step &
        chunked prefill")."""
        floor_ = max(self.max_batch_slots, int(self.min_step_tokens or 0))
        if total <= floor_:
            return floor_
        return max(_MIN_GRID_TOKENS, 1 << (int(total) - 1).bit_length())

    def _make_step(self) -> jit.StaticFunction:
        """THE unified ragged step program (tentpole of ISSUE 11): one
        compiled function serving every prefill/decode mix. Inputs ride
        as data, shapes only as the token-grid bucket T:

        - ``tok`` [T, 1] — every query token this step, flattened: one
          row per decode slot, one row per prompt-chunk token,
        - ``tok_pos`` [T] — each row's absolute position,
        - ``tok_bt`` [T, pages_per_seq] — each row's OWNER's block table
          (a chunk repeats its slot's table row per token),
        - ``sample_rows`` [B, S] — grid rows where each slot's samples
          read logits (S = spec_k+1: the slot's last/chunk-final token
          plus its draft rows; column 0 is the pre-speculation
          ``last_row``, unused columns and idle slots point at row 0 and
          are discarded on host),
        - ``sample_pos`` [B, S] — the positions that key each sample,
        - ``tok_adp`` [T] — each row's OWNER's adapter slot in the
          stacked LoRA arrays (0 = reserved zero-delta identity),
        - ``temps``/``seeds`` [B] — per-slot sampling params,
        - ``fsm_state`` [B, S] — each sample's ABSOLUTE grammar-table
          row (0 = reserved all-True identity row; draft columns carry
          host-precomputed hypothetical states),
        - ``grammar_table`` [grammar_states, V] — the interned DFA
          allow-masks, one device table for every live grammar,
        - ``*rest`` — the stacked adapter (A, B) arrays per site, then
          the paged KV pools, consumed and returned functionally.

        Adapters and grammars are ALWAYS in the program — disabled is a
        VALUE (slot 0's zero weights add exactly 0.0; row 0's all-True
        mask selects the raw logits bitwise), never a branch, so
        adapter/grammar on/off shares one compiled signature and
        ``compile_counts()`` stays pinned (ISSUE 16).

        The trunk's ``forward_paged`` treats every row as "one token at
        an arbitrary position over an arbitrary page list" — which is
        the whole ragged trick (ops/pallas/paged_attention.py "Ragged
        form"): each layer scatters ALL T rows' KV into the pool first,
        then gathers per-row attention masked at the row's own position,
        so chunk tokens causally see their chunk-mates, decode rows are
        untouched by them, and a DRAFT row at position p+j attends the
        KV its burst-mates scattered this very step — speculation's
        in-step causality for free. Sampling gathers the B*S sample
        rows BEFORE the vocab matmul (the [V] projection runs on B*S
        rows, not T) and derives per-row keys
        fold_in(PRNGKey(seed), sample_pos) — the _sample_key contract,
        traced: a draft row's target at position p+j is EXACTLY the
        token the stream would sample there without speculation, which
        is why acceptance-by-equality preserves bit-identical streams."""
        trunk, model, n_layers = self.trunk, self.model, self.n_layers
        site_names = [s for s, _, _ in self.adapters.sites]
        n_adp = 2 * len(site_names)
        # pool arrays per layer: (k, v) for bf16/f32 pools, (k, v,
        # k_scales, v_scales) for int8 — the stride is a Python constant
        # at trace time, so quantization changes WHICH arrays ride as
        # data, never the program count
        stride = self.pool.step_stride

        def step_fn(tok, tok_pos, tok_bt, tok_adp, sample_rows, sample_pos,
                    temps, seeds, fsm_state, grammar_table, *rest):
            adp_flat, flat_pools = rest[:n_adp], rest[n_adp:]
            caches = [tuple(flat_pools[stride * i: stride * (i + 1)])
                      for i in range(n_layers)]
            with no_grad():
                # per-row adapter gather: every grid row pulls ITS
                # owner's (A, B) stack by index — slot 0 rows pull the
                # zero identity, so the delta below is + 0.0 exactly
                adapters = {}
                for si, site in enumerate(site_names):
                    ga = apply_op(
                        lambda a, ix: a[ix.reshape(-1).astype(jnp.int32)],
                        [ensure_tensor(adp_flat[2 * si]),
                         ensure_tensor(tok_adp)],
                        name="gather_adapter_a")
                    gb = apply_op(
                        lambda b, ix: b[ix.reshape(-1).astype(jnp.int32)],
                        [ensure_tensor(adp_flat[2 * si + 1]),
                         ensure_tensor(tok_adp)],
                        name="gather_adapter_b")
                    adapters[site] = (ga, gb)
                hidden, ncs = trunk.forward_paged(tok, tok_pos, tok_bt,
                                                  caches, adapters=adapters)
                # per-slot sample rows gathered BEFORE the vocab matmul:
                # the grid carries up to token-budget rows but only
                # max_batch_slots * (spec_k+1) of them sample
                last_h = apply_op(
                    lambda h, li: h[li.reshape(-1).astype(jnp.int32)],
                    [ensure_tensor(hidden), ensure_tensor(sample_rows)],
                    name="gather_sample_rows")
                logits = model.logits(last_h)
            last = apply_op(lambda lv: lv[:, -1, :].astype(jnp.float32),
                            [ensure_tensor(logits)], name="last_logits")
            # per-slot finite flag BEFORE sampling: the host quarantines
            # any slot whose logits went NaN/inf (poisoned KV, numeric
            # blowup) without ever trusting its sampled token — and
            # because it rides in the same program, the check costs one
            # fused reduction, not a second compile. Mid-prompt chunks
            # get the same canary: their sample row is real compute even
            # though its sample is discarded.
            fin = apply_op(
                lambda lv: jnp.isfinite(lv).all(axis=-1),
                [last], name="logits_finite")
            # constrained decoding: each sample row gathers its DFA
            # state's allow-mask from the ONE interned grammar table and
            # masks disallowed tokens to -1e30 BEFORE sampling — so
            # greedy, temperature, and draft-target sampling are all
            # constrained by the same op. Row 0 is all-True:
            # where(True, lv, -1e30) IS lv, bitwise — the grammar-off
            # identity that keeps this in the one compiled signature.
            # NaN-quarantine ordering: fin reads the RAW logits above,
            # so a poisoned row still trips the canary even if the mask
            # would have hidden its non-finite lanes.
            masked = apply_op(
                lambda lv, gt, fs: jnp.where(
                    gt[fs.reshape(-1).astype(jnp.int32)], lv,
                    jnp.float32(-1e30)),
                [last, ensure_tensor(grammar_table),
                 ensure_tensor(fsm_state)], name="grammar_mask")

            def batched_sample(lv, tv, sv, pv):
                # per-row key = fold_in(PRNGKey(seed), position) — the
                # _sample_key contract, traced: each request samples
                # from ITS OWN stream, so its tokens are a pure function
                # of (prompt, seed, temperature) no matter which
                # batch-mates ride the grid, how its prompt was chunked,
                # or which engine runs it. seeds and positions are DATA:
                # no recompile, and an idle sample row's (0, 0) key
                # samples masked garbage that the host discards as
                # before. lv is [B*S, V]; temps/seeds broadcast across
                # each slot's S sample rows (one request, one stream),
                # positions arrive per row — a draft row at p+j samples
                # with the SAME key the plain decode at p+j would use.
                S = pv.shape[1]
                tvf = jnp.repeat(tv.astype(jnp.float32), S)
                svf = jnp.repeat(sv, S)
                pvf = pv.reshape(-1)
                greedy = jnp.argmax(lv, axis=-1).astype(jnp.int32)
                t = jnp.maximum(tvf, 1e-6)

                def one_row(seed_i, pos_i, row):
                    key = jax.random.fold_in(jax.random.PRNGKey(seed_i),
                                             pos_i)
                    return jax.random.categorical(key, row)

                sampled = jax.vmap(one_row)(
                    svf, pvf, lv / t[:, None]).astype(jnp.int32)
                return jnp.where(tvf > 0, sampled, greedy)

            nxt = apply_op(batched_sample,
                           [masked, ensure_tensor(temps),
                            ensure_tensor(seeds), ensure_tensor(sample_pos)],
                           name="serve_sample")
            flat = [t for c in ncs for t in c]
            return (nxt, fin, *flat)

        # "the step compiles once per bucket" becomes monitorable:
        # jit_compiles_total{fn="serving_step"} must pin at the
        # bucket-set size. cache_key_extra folds the model architecture
        # and pool geometry into the persistent compile-cache key:
        # config values are baked into the traced program as CONSTANTS,
        # invisible to the shape-only spec key, so two engines whose
        # pools merely have equal shapes must not share an executable.
        step_fn.__name__ = "serving_step"
        cfg = self.model.config
        extra = repr((type(self.model).__name__, sorted(
            (k, v) for k, v in vars(cfg).items()
            if isinstance(v, (bool, int, float, str, type(None)))),
            self.page_size, self.pages_per_seq, self._spec_rows,
            self.adapters.capacity, self.adapters.rank,
            self._grammar_cap, str(jnp.dtype(self.pool.dtype))))
        return jit.StaticFunction(step_fn, observe=[self.model],
                                  warmup=False, dy2static=False,
                                  cache_dir=self._compile_cache_dir,
                                  cache_key_extra=extra)

    def _step_once(self) -> List[RequestOutput]:
        t0 = time.perf_counter()
        B = self.max_batch_slots
        finished: List[RequestOutput] = []
        decode_idx: List[int] = []
        prefill_info = []
        for i, st in enumerate(self.slots):
            if st is None:
                continue
            if st.parked:
                # parked slot: zero rows this step — its KV lives on the
                # host tier and its block table holds null sentinels
                continue
            if (self._host_offload
                    and self.pool.offloaded_pages(st.req.req_id)):
                # LATE prefetch: an active slot reached the step path
                # with pages still on the host (unpark restored the flag
                # but not the pages, or a caller flipped `parked` by
                # hand). Restore NOW — blocking, which is exactly the
                # stall the unpark-time prefetch exists to avoid — and
                # count it so operators can see the policy miss
                try:
                    n = self.pool.prefetch_seq(st.req.req_id)
                    self._m_prefetch_late.inc(float(n))
                except Exception as e:
                    finished.append(self._retire_abnormal(
                        st, slot=i, reason="error", error=e))
                    continue
            if st.prefilling:
                prefill_info.append((i, int(st.ids.size) - st.pos, st.req))
            else:
                decode_idx.append(i)
        # brownout hooks (overload.OverloadController): both are pure
        # planning data — chunk sizes and draft gating never touch the
        # compiled step's shape set, so the compile surface is invariant
        # across every ladder level
        _ovl = self._overload
        chunks = self.scheduler.plan_chunks(
            len(decode_idx), prefill_info,
            batch_cap=None if _ovl is None else _ovl.chunk_cap(),
            batch_priority=(2 if _ovl is None
                            else _ovl.config.batch_priority))
        for i, c in chunks:
            self._trace.emit("req.chunk_planned",
                             self.slots[i].req.req_id, arg=float(c))

        # speculative drafts ride the budget's LEFTOVER only — charged
        # strictly after decode tokens and prompt chunks, so speculation
        # can never displace a running stream's next token or slow a
        # prefill (scheduler.plan_drafts splits the remainder in the
        # same SLO order as chunks). Each slot's draft count is further
        # capped so the burst can never overrun max_new_tokens (the base
        # decode emits >= 1, hence remaining-1) or the request's page
        # reservation / context window.
        drafts: Dict[int, np.ndarray] = {}
        if (self.drafter is not None and decode_idx
                and not (_ovl is not None and _ovl.drafts_paused)):
            leftover = (self.token_budget - len(decode_idx)
                        - sum(c for _, c in chunks))
            if leftover > 0:
                wants = []
                for i in decode_idx:
                    st = self.slots[i]
                    limit = min(
                        int(st.req.prompt.size) + int(st.req.max_new_tokens),
                        self.max_model_len)
                    cap = min(self.spec_k,
                              int(st.req.max_new_tokens) - len(st.gen) - 1,
                              limit - (st.pos + 1))
                    if cap > 0:
                        wants.append((i, cap, st.req))
                for i, d in self.scheduler.plan_drafts(leftover, wants):
                    st = self.slots[i]
                    # the full stream so far: prompt + gen covers a
                    # migrated request too (gen is journal-seeded), so
                    # drafting is migration-invariant like sampling
                    prop = self.drafter.propose(
                        np.concatenate([st.req.prompt,
                                        np.asarray(st.gen, np.int32)]), d)
                    prop = np.asarray(prop, np.int32).reshape(-1)[:d]
                    if st.fsm is not None and prop.size:
                        # constrained slot: keep only the longest
                        # grammar-valid prefix of the proposal — an
                        # invalid draft could never equal its (masked)
                        # target, so rows past the first violation are
                        # guaranteed-wasted compute, and the hypothetical
                        # FSM states its sample columns need would not
                        # even exist
                        s_, keep = st.fsm_state, 0
                        for t_ in prop:
                            s_ = st.fsm.next_state(s_, int(t_))
                            if s_ < 0:
                                break
                            keep += 1
                        if keep < prop.size:
                            self._m_grammar_filtered.inc(
                                int(prop.size) - keep)
                            prop = prop[:keep]
                    if prop.size:
                        drafts[i] = prop
                        self._trace.emit("req.drafts", st.req.req_id,
                                         arg=float(prop.size))

        # KV room per slot BEFORE the compiled step: decode rows reserve
        # this step's writes via extend()/extend_write() (not
        # append_token — a step aborted after this loop re-reserves the
        # SAME positions on retry instead of drifting _lens one phantom
        # token per aborted step); a slot with draft rows reserves the
        # whole burst range like a chunk does (CoW seam included —
        # rejected drafts roll back by pool.truncate, which relies on
        # this exclusivity); chunk rows reserve their whole range via
        # extend_write. Out of pages (impossible unless injected/
        # buggy): quarantine the victim, keep the rest of the batch —
        # its row simply never joins the grid.
        rows = []  # (slot, token ids [c], positions [c], is_chunk, n_draft)
        n_decode_tokens = 0
        n_draft_tokens = 0
        for i in decode_idx:
            st = self.slots[i]
            d_toks = drafts.get(i)
            d = 0 if d_toks is None else int(d_toks.size)
            try:
                if d:
                    self.pool.extend_write(st.req.req_id, st.pos,
                                           st.pos + 1 + d)
                else:
                    self.pool.extend(st.req.req_id, st.pos + 1)
            except Exception as e:
                finished.append(
                    self._retire_abnormal(st, slot=i, reason="error",
                                          error=e))
                continue
            toks = (np.concatenate([[st.last_token], d_toks]).astype(np.int32)
                    if d else np.asarray([st.last_token], np.int32))
            rows.append((i, toks,
                         np.arange(st.pos, st.pos + 1 + d, dtype=np.int32),
                         False, d))
            n_decode_tokens += 1
            n_draft_tokens += d
        for i, c in chunks:
            st = self.slots[i]
            try:
                self.pool.extend_write(st.req.req_id, st.pos, st.pos + c)
            except Exception as e:
                finished.append(
                    self._retire_abnormal(st, slot=i, reason="error",
                                          error=e))
                continue
            rows.append((i, st.ids[st.pos:st.pos + c],
                         np.arange(st.pos, st.pos + c, dtype=np.int32),
                         True, 0))
        faults.point("serving.decode_step")
        if not rows:
            return finished
        total = sum(r[1].size for r in rows)
        T = self._grid_tokens(total)
        # a bucket this engine never ran compiles (or deserializes from
        # the disk cache) inside the coming program call — remember it
        # now so the wall time lands in the trace's compile bucket
        fresh_bucket = T not in self._grid_buckets_seen
        self._grid_buckets_seen.add(T)
        S = self._spec_rows
        tok = np.zeros((T, 1), np.int32)
        tok_pos = np.zeros(T, np.int32)
        tok_bt = np.zeros((T, self.pages_per_seq), np.int32)
        tok_adp = np.zeros(T, np.int32)
        sample_rows = np.zeros((B, S), np.int32)
        sample_pos = np.zeros((B, S), np.int32)
        temps = np.zeros(B, np.float32)
        seeds = np.zeros(B, np.int32)
        # absolute grammar-table rows per sample; idle/unconstrained
        # entries stay 0 = the all-True identity row (mask is a no-op)
        fsm_state = np.zeros((B, S), np.int32)
        cur = 0
        for i, toks, poss, is_chunk, d in rows:
            st = self.slots[i]
            c = toks.size
            tok[cur:cur + c, 0] = toks
            tok_pos[cur:cur + c] = poss
            table = self.pool.block_table(st.req.req_id)
            tok_bt[cur:cur + c, :len(table)] = table
            tok_adp[cur:cur + c] = st.adp_slot
            if is_chunk:
                sample_rows[i, 0] = cur + c - 1
                sample_pos[i, 0] = int(poss[-1])
                if st.fsm is not None:
                    # only the FINAL chunk's sample lands, and it is the
                    # stream's next token — mask it at the current (post-
                    # journal) DFA state; mid-prompt chunks' discarded
                    # samples get the same row harmlessly
                    fsm_state[i, 0] = st.fsm_off + st.fsm_state
            else:
                # base decode row + its d draft rows are contiguous:
                # sample column j targets position pos+j, i.e. the token
                # FOLLOWING the j-th burst token
                sample_rows[i, :d + 1] = np.arange(cur, cur + d + 1)
                sample_pos[i, :d + 1] = poss
                if st.fsm is not None:
                    # column j masks the token AFTER burst token j, so it
                    # needs the HYPOTHETICAL state once drafts 1..j have
                    # landed — host-walked here; drafts were pre-filtered
                    # to grammar-valid, so the walk stays live. Without
                    # this, unmasked draft targets could never match a
                    # constrained stream and acceptance would collapse.
                    s_ = st.fsm_state
                    fsm_state[i, 0] = st.fsm_off + s_
                    for j in range(1, d + 1):
                        s_ = st.fsm.next_state(s_, int(toks[j]))
                        fsm_state[i, j] = st.fsm_off + s_
            temps[i] = st.req.temperature
            seeds[i] = st.req.seed
            cur += c
        if self._step_prog is None:
            fresh_bucket = True
            self._step_prog = self._compile_with_retry(
                "serving.compile_step", self._make_step)
        t_prog = time.perf_counter()
        res = self._step_prog(
            Tensor(jnp.asarray(tok)), Tensor(jnp.asarray(tok_pos)),
            Tensor(jnp.asarray(tok_bt)), Tensor(jnp.asarray(tok_adp)),
            Tensor(jnp.asarray(sample_rows)),
            Tensor(jnp.asarray(sample_pos)), Tensor(jnp.asarray(temps)),
            Tensor(jnp.asarray(seeds)), Tensor(jnp.asarray(fsm_state)),
            self._grammar_device,
            *self.adapters.arrays(),
            *[p for i in range(self.n_layers)
              for p in self.pool.step_arrays(i)])
        nxt, fin, flat = res[0], res[1], res[2:]
        self.pool.set_step_flat(flat)
        if self.pool.quantized and total:
            # absmax-floor accounting for THIS step's written slots: a
            # clipped scale means a (page, pos, head) row whose KV
            # underflowed the quantizer's dynamic range (kv_cache docs)
            w_pages = tok_bt[np.arange(total),
                             tok_pos[:total] // self.page_size]
            live = w_pages > 0
            if live.any():
                self.pool.record_scale_clips(
                    w_pages[live], (tok_pos[:total] % self.page_size)[live])
        nxt_host = np.asarray(nxt.numpy()).reshape(B, S)
        fin_host = np.asarray(fin.numpy()).reshape(B, S).astype(bool)
        now = time.perf_counter()
        self._m_decode.observe(now - t0)
        self._m_mix_decode.observe(n_decode_tokens)
        self._m_mix_draft.observe(n_draft_tokens)
        self._m_mix_prefill.observe(total - n_decode_tokens
                                    - n_draft_tokens)
        if fresh_bucket:
            # a fresh token-grid bucket compiled inside this program
            # call: charge its wall time to every rider, so the
            # attribution pass can name compile (not prefill) as where
            # a cold request's TTFT went
            for _ci, _ct, _cp, _cic, _cd in rows:
                _cst = self.slots[_ci]
                if _cst is not None:
                    self._trace.emit("req.compile", _cst.req.req_id,
                                     arg=now - t_prog, t=now)

        for i, toks, poss, is_chunk, d in rows:
            st = self.slots[i]
            if st is None:
                # an earlier row's callback cancelled THIS slot's
                # request reentrantly — touching it again would
                # double-free its pages (no admission runs mid-step, so
                # a non-None slot is still the row's own state)
                continue
            n_sample = 1 if is_chunk else d + 1
            if not fin_host[i, :n_sample].all():
                # NaN/inf logits on the slot's sample row: quarantine
                # ONLY this sequence — its sampled token is garbage and
                # is never appended (for a chunk, the KV it wrote is as
                # untrustworthy as the sample); pages return to the pool
                # now; batch-mates are untouched because attention
                # gathers strictly via block tables. Mid-prompt chunks
                # get the same canary, so poison never survives to a
                # later chunk.
                if is_chunk:
                    st.pos += toks.size
                    self._m_chunk.observe(toks.size)
                finished.append(
                    self._retire_abnormal(st, slot=i, reason="nan"))
                continue
            if is_chunk:
                c = toks.size
                st.pos += c
                self._m_chunk.observe(c)
                self._trace.emit("req.chunk", st.req.req_id,
                                 arg=float(c), t=now)
                if st.prefilling:
                    continue  # mid-prompt: more chunks to go, no token
                # FINAL chunk: the sample at position len(ids)-1 IS the
                # stream's next token (first generated, or the journal's
                # successor for a migrated request — key position s-1
                # matches the decode the dead engine would have run)
                cache = (self.prefix_cache if st.req.prefix_cache
                         else None)
                if cache is not None:
                    # index this prompt's full pages for the next
                    # admission (prompt only — journal/generated tokens
                    # are per-request noise); the created nodes ride the
                    # slot state so a NaN quarantine can evict exactly
                    # what THIS request contributed
                    st.inserted_nodes = cache.insert(
                        st.req.prompt, int(st.req.prompt.size),
                        self.pool.block_table(st.req.req_id))
                self._m_prefill.observe(now - st.t_admit)
                if not st.req.resume_tokens:
                    # a resumed request's first token landed long ago
                    self._m_ttft.observe(now - st.req.arrival_t)
                out = self._land_token(st, slot=i,
                                       token=int(nxt_host[i, 0]), now=now)
                if out is not None:
                    finished.append(out)
                continue
            # decode burst: sample column j holds the stream's token at
            # position pos+j+1 — the EXACT token a plain decode would
            # sample there (same fold_in key, same logits given the same
            # prefix). Accept the longest prefix of drafts that equals
            # those targets, then land accepted drafts' targets plus the
            # free "bonus" token from the first mismatching (or final)
            # column. Rejected draft rows wrote KV for tokens the stream
            # never took: roll the pool length back BEFORE landing (a
            # landed token may retire the request and free its pages).
            targets = nxt_host[i, :d + 1]
            a = 0
            while a < d and int(toks[a + 1]) == int(targets[a]):
                a += 1
            if d:
                self._m_spec_drafted.inc(d)
                self._m_spec_accepted.inc(a)
                self._m_spec_accept.observe(a / d)
                self._trace.emit("req.spec_accept", st.req.req_id,
                                 arg=float(a), t=now)
                if a < d:
                    self._trace.emit("req.spec_reject", st.req.req_id,
                                     arg=float(d - a), t=now)
                    self.pool.truncate(st.req.req_id, st.pos + a + 1)
            for t in targets[:a + 1]:
                st.pos += 1
                # per-sequence inter-token latency: the streaming SLO —
                # step time plus any step this sequence sat through
                # (accepted drafts land with near-zero gaps: speculation
                # collapses ITL, which is the whole point)
                self._m_itl.observe(now - st.t_last)
                out = self._land_token(st, slot=i, token=int(t), now=now)
                if out is not None:
                    finished.append(out)
                    break
                if self.slots[i] is not st:
                    break  # reentrant cancel inside the stream callback
        return finished

    def _land_token(self, st: _SeqState, slot: int, token: int,
                    now: float) -> Optional[RequestOutput]:
        """ONE copy of the token-landing choreography, shared by the
        final-chunk first token and every decode token: append to the
        journal, advance the grammar DFA, stream it (isolated,
        reentrant-cancel-aware), and retire on eos/length/grammar-
        complete. Returns the retirement output, if any."""
        st.last_token = token
        st.gen.append(token)
        st.t_last = now
        self._m_tokens.inc()
        self.stats["generated_tokens"] += 1
        self._trace.emit("req.token", st.req.req_id,
                         arg=float(len(st.gen) - 1), t=now)
        if st.fsm is not None and (st.req.eos_token_id is None
                                   or token != st.req.eos_token_id):
            # host mirror of the device mask: the DFA walks every landed
            # non-eos token (the mask guarantees it is allowed, so the
            # walk can't die; eos is terminal and has no DFA edge)
            nxt = st.fsm.next_state(st.fsm_state, token)
            if nxt >= 0:
                st.fsm_state = nxt
            self._m_grammar_tokens.inc()
            self._trace.emit("req.grammar_mask", st.req.req_id,
                             arg=float(st.fsm_state), t=now)
        if st.req.stream_cb is not None:
            cb_err = self._safe_cb(st.req, token, False, len(st.gen) - 1)
            if self.slots[slot] is not st:
                # cancel() ran inside the callback and already retired
                # this sequence — touching it again would double-free
                return None
            if cb_err is not None:
                return self._retire_abnormal(st, slot=slot,
                                             reason="error", error=cb_err)
        return self._maybe_retire(st, slot=slot)

    @staticmethod
    def _sample_key(seed, position):
        """THE determinism contract, in one line: the key that samples
        the token following ``position`` (0-based index of the last
        consumed token) is ``fold_in(PRNGKey(seed), position)`` — a pure
        function of (request seed, stream position). The compiled step
        computes the identical expression per slot (traced, vmapped) for
        final-chunk first tokens and decode tokens alike — threefry is
        deterministic, so every engine derives bit-equal keys and a
        request's sampled stream is independent of batch composition,
        chunk boundaries, engine history, and any migration."""
        return jax.random.fold_in(jax.random.PRNGKey(seed), position)

    # -------------------------------------------------------------- retire
    def _maybe_retire(self, st: _SeqState,
                      slot: int) -> Optional[RequestOutput]:
        req = st.req
        hit_eos = (req.eos_token_id is not None
                   and st.last_token == req.eos_token_id)
        # a constrained request whose DFA can only accept is DONE — the
        # mask admits no further token, so decoding past this point
        # would sample from an all -1e30 row
        done_fsm = st.fsm is not None and st.fsm.is_complete(st.fsm_state)
        if not (hit_eos or done_fsm) and len(st.gen) < req.max_new_tokens:
            return None
        if st.fsm is not None:
            valid = st.fsm.is_accepting(st.fsm_state)
            self._m_grammar_completions.labels(
                result="valid" if valid else "invalid", **self._lbl).inc()
        self._grammar_release(st)
        # retire NOW: pages go back to the pool this very step (has_seq
        # guard: a reentrant cancel from the terminal-token's stream
        # callback may have freed them already)
        if self.pool.has_seq(req.req_id):
            self.pool.free(req.req_id)
        self.slots[slot] = None
        self._m_requests.labels(event="retired", **self._lbl).inc()
        self.stats["finished_requests"] += 1
        out = RequestOutput(req_id=req.req_id,
                            prompt_token_ids=req.prompt,
                            token_ids=list(st.gen),
                            finish_reason=("stop" if hit_eos or done_fsm
                                           else "length"))
        self._trace.emit("req.retire", req.req_id,
                         label=out.finish_reason)
        self._outputs[out.req_id] = out  # eager: survives a later raise
        if req.stream_cb is not None:
            # terminal call: `finished` is the reason string (truthy, so
            # bool-style `if finished:` consumers keep working); isolated
            # like every callback — a raise here only records
            self._safe_cb(req, None, out.finish_reason, len(st.gen))
        return out
