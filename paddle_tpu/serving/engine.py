"""Continuous-batching LLM inference engine over the paged KV cache.

The serving counterpart of ``GenerationMixin.generate`` (one static batch,
dense caches): requests join and retire MID-DECODE. The engine keeps a
fixed grid of ``max_batch_slots`` decode slots; each engine step

1. **admits** waiting requests FCFS into free slots (scheduler.py) under
   the prefill token budget and the pool's worst-case page accounting,
2. **prefills** each admitted prompt through the model's dense-cache path
   at a power-of-two padded bucket length (bounded prefill program count),
   scatters the prompt KV into the sequence's pages, and samples the
   first token,
3. runs ONE **compiled decode step** for every live slot at once — shapes
   padded to the slot grid, block tables and positions riding in as data —
   so XLA compiles the decode program exactly once no matter how the live
   batch churns (asserted by tests via :meth:`compile_counts`),
4. **retires** finished sequences (eos or max tokens), freeing their pages
   immediately for the next admission.

Idle slots carry the null block table (all page 0) and a zero position;
their masked garbage rides along and is discarded on the host. Per-token
streaming goes through each request's ``stream_cb``.

Telemetry (docs/OBSERVABILITY.md): every step feeds the always-on
``paddle_tpu.metrics`` registry — TTFT / inter-token-latency / queue-wait
/ step-time histograms, request lifecycle counters, and page/queue gauges
(the latter via ``profiler.record_counter``, which ALSO lands them in the
chrome trace next to the ``engine_step`` spans whenever a profiler is
recording). ``engine.stats`` stays a thin per-step dict view over the
same numbers.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import jit, metrics
from ..autograd.engine import no_grad
from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor
from .kv_cache import PagedKVCachePool
from .scheduler import FCFSScheduler, Request, RequestOutput

__all__ = ["ServingEngine"]

_MIN_PREFILL_BUCKET = 16


def _bucket(n: int, cap: int) -> int:
    """Power-of-two prefill padding: program count is O(log max_len)."""
    b = max(_MIN_PREFILL_BUCKET, 1 << (int(n) - 1).bit_length())
    return min(b, cap)


class _SeqState:
    """One live slot: request + decode cursor."""

    __slots__ = ("req", "pos", "last_token", "gen", "key", "t_last")

    def __init__(self, req: Request, pos: int, last_token: int, key):
        self.req = req
        self.pos = pos              # tokens of KV written so far
        self.last_token = last_token
        self.gen = [last_token]     # generated ids (incl. eos when hit)
        self.key = key
        self.t_last = time.perf_counter()  # last token's landing time (ITL)


class ServingEngine:
    """Continuous-batching engine for any ``GenerationMixin`` model
    (LlamaForCausalLM / GPTForCausalLM): paged KV pool + FCFS scheduler +
    a single compiled ragged-paged-attention decode step.

    ``num_pages=None`` sizes the pool for ``max_batch_slots`` worst-case
    sequences of ``max_model_len`` tokens (+1 null page); pass an explicit
    page count (see docs/SERVING.md for the HBM sizing math) to serve more
    queued requests than fit concurrently — admission simply waits.
    """

    def __init__(self, model, *, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_batch_slots: int = 8,
                 max_model_len: Optional[int] = None,
                 prefill_token_budget: int = 1024,
                 kv_dtype=jnp.float32, seed: int = 0):
        self.model = model
        model.eval()
        self.trunk = model._decode_trunk()
        n_layers, n_kv, head_dim = model._cache_spec()
        self.n_layers = n_layers
        cfg_max = int(model.config.max_position_embeddings)
        self.max_model_len = min(int(max_model_len or cfg_max), cfg_max)
        self.page_size = int(page_size)
        self.max_batch_slots = int(max_batch_slots)
        self.pages_per_seq = -(-self.max_model_len // self.page_size)
        if num_pages is None:
            num_pages = self.max_batch_slots * self.pages_per_seq + 1
        self.pool = PagedKVCachePool(n_layers, num_pages, self.page_size,
                                     n_kv, head_dim, dtype=kv_dtype)
        self.scheduler = FCFSScheduler(self.max_batch_slots,
                                       prefill_token_budget)
        self.slots: List[Optional[_SeqState]] = [None] * self.max_batch_slots
        self._decode_prog = None
        self._prefill_progs: Dict[int, jit.StaticFunction] = {}
        self._rng = jax.random.PRNGKey(seed)
        self._outputs: Dict[object, RequestOutput] = {}
        self.stats: Dict[str, float] = {
            "steps": 0, "generated_tokens": 0, "finished_requests": 0,
            "queue_depth": 0, "running_seqs": 0, "tokens_per_sec": 0.0,
            "page_utilization": 0.0, "peak_pages": 0,
        }
        # typed instruments (docs/OBSERVABILITY.md catalog) — the stats
        # dict above stays a thin per-step view over these
        reg = metrics.get_registry()
        self._m_ttft = reg.histogram(
            "paddle_tpu_serving_ttft_seconds",
            "Time to first token: request enqueue -> first sampled token")
        self._m_itl = reg.histogram(
            "paddle_tpu_serving_inter_token_seconds",
            "Inter-token latency: gap between consecutive tokens of one "
            "sequence during decode")
        self._m_step = reg.histogram(
            "paddle_tpu_serving_step_seconds",
            "Full engine step: admit + prefill + batched decode + retire")
        self._m_prefill = reg.histogram(
            "paddle_tpu_serving_prefill_seconds",
            "One request's prefill: bucketed forward + KV scatter + "
            "first-token sample")
        self._m_decode = reg.histogram(
            "paddle_tpu_serving_decode_step_seconds",
            "One batched decode step over all live slots")
        self._m_requests = reg.counter(
            "paddle_tpu_serving_requests_total",
            "Requests by lifecycle event", labels=("event",))
        self._m_tokens = reg.counter(
            "paddle_tpu_serving_generated_tokens_total",
            "Tokens sampled by the engine (prefill first tokens included)")
        for ev in ("admitted", "rejected", "retired", "preempted"):
            self._m_requests.labels(event=ev)  # pre-create: scrapes show 0

    # ------------------------------------------------------------ frontend
    def check_request(self, prompt_len: int, max_new_tokens: int) -> None:
        """Raise ValueError if a request of this shape could NEVER be
        served — batch front doors call this for every prompt before
        queueing any, so one bad prompt can't strand its batch-mates."""
        total = int(prompt_len) + int(max_new_tokens)
        if total > self.max_model_len:
            self._m_requests.labels(event="rejected").inc()
            raise ValueError(
                f"prompt {prompt_len} + max_new_tokens {max_new_tokens} "
                f"exceeds max_model_len {self.max_model_len}")
        need = self.pool.pages_needed(total)
        if need > self.pool.usable_pages:
            # even an empty pool could never admit it — rejecting here
            # (not queueing) keeps run() from spinning forever on a head
            # request that can never pass can_admit
            self._m_requests.labels(event="rejected").inc()
            raise ValueError(
                f"request needs {need} KV pages worst-case but the pool "
                f"has {self.pool.usable_pages} usable pages — raise "
                f"num_pages or lower max_new_tokens")

    def add_request(self, prompt, max_new_tokens: int = 32,
                    temperature: float = 0.0,
                    eos_token_id: Optional[int] = None, seed: int = 0,
                    stream_cb=None):
        """Queue a request; returns its ``req_id``. Generation starts at
        the next :meth:`step` with capacity (continuous batching — no
        barrier on the current batch)."""
        req = Request(prompt=np.asarray(prompt, np.int32).reshape(-1),
                      max_new_tokens=max_new_tokens, temperature=temperature,
                      eos_token_id=eos_token_id, seed=seed,
                      stream_cb=stream_cb)
        self.check_request(req.prompt.size, req.max_new_tokens)
        self.scheduler.add(req)
        return req.req_id

    @property
    def has_work(self) -> bool:
        return bool(self.scheduler.waiting) or any(
            s is not None for s in self.slots)

    def run(self) -> Dict[object, RequestOutput]:
        """Drive :meth:`step` until queue and slots drain; returns every
        request finished since the last :meth:`run` (including ones that
        retired in explicit :meth:`step` calls in between), keyed by
        ``req_id``. Draining — outputs are handed out exactly once, so a
        long-lived server never accumulates them."""
        while self.has_work:
            self.step()
        out, self._outputs = self._outputs, {}
        return out

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program tally — the recompilation bound the tests
        assert on: decode stays at 1 signature forever; prefill grows one
        program per power-of-two bucket."""
        d = len(self._decode_prog._cache) if self._decode_prog else 0
        p = sum(len(f._cache) for f in self._prefill_progs.values())
        return {"decode": d, "prefill": p,
                "prefill_buckets": len(self._prefill_progs)}

    # ---------------------------------------------------------------- step
    def step(self) -> List[RequestOutput]:
        """One engine iteration: admit → prefill → batched decode →
        retire. Returns requests that finished during this step."""
        from ..profiler import RecordEvent, record_counter

        t0 = time.perf_counter()
        tokens_before = self.stats["generated_tokens"]
        finished: List[RequestOutput] = []
        with RecordEvent("engine_step"):
            free = sum(1 for s in self.slots if s is None)
            for req in self.scheduler.admit(free, self.pool):
                self._m_requests.labels(event="admitted").inc()
                out = self._prefill(req)
                if out is not None:
                    finished.append(out)
            if any(s is not None for s in self.slots):
                finished.extend(self._decode_once())
        dt = time.perf_counter() - t0
        self._m_step.observe(dt)
        self.stats["steps"] += 1
        self.stats["queue_depth"] = self.scheduler.queue_depth
        self.stats["running_seqs"] = sum(
            1 for s in self.slots if s is not None)
        # zero-duration guard: a clock with coarse resolution can report
        # dt == 0 for an idle step — a rate of 0 beats a ZeroDivisionError
        # (or the absurd spike 1e-9 used to produce)
        tokens_this_step = self.stats["generated_tokens"] - tokens_before
        self.stats["tokens_per_sec"] = (
            tokens_this_step / dt if dt > 0.0 else 0.0)
        self.stats["page_utilization"] = self.pool.utilization()
        self.stats["peak_pages"] = self.pool.peak_used
        record_counter("serving.queue_depth", self.stats["queue_depth"])
        record_counter("serving.running_seqs", self.stats["running_seqs"])
        record_counter("serving.tokens_per_sec",
                       self.stats["tokens_per_sec"])
        record_counter("serving.page_utilization",
                       self.stats["page_utilization"])
        for out in finished:
            self._outputs[out.req_id] = out
        return finished

    # ------------------------------------------------------------- prefill
    def _make_prefill(self, bucket: int) -> jit.StaticFunction:
        trunk, model, n_layers = self.trunk, self.model, self.n_layers

        def prefill_fn(ids, last_pos, *flat_caches):
            caches = [(flat_caches[2 * i], flat_caches[2 * i + 1])
                      for i in range(n_layers)]
            with no_grad():
                hidden, ncs = trunk(ids, caches=caches,
                                    cur_len=Tensor(jnp.zeros((), jnp.int32),
                                                   stop_gradient=True))
                # slice the last REAL position before the vocab matmul:
                # the padded bucket tail never touches the [V] projection
                last_h = apply_op(
                    lambda h, lp: jax.lax.dynamic_slice(
                        h, (jnp.int32(0), lp.astype(jnp.int32).reshape(()),
                            jnp.int32(0)),
                        (1, 1, h.shape[-1])),
                    [ensure_tensor(hidden), ensure_tensor(last_pos)],
                    name="prefill_last_hidden")
                logits = model.logits(last_h)
            last = apply_op(lambda lv: lv[:, -1, :].astype(jnp.float32),
                            [ensure_tensor(logits)], name="last_logits")
            flat = [t for c in ncs for t in c]
            return (last, *flat)

        # the compile counter labels by function name — make recompiles
        # attributable on /metrics (jit_compiles_total{fn="serving_prefill"})
        prefill_fn.__name__ = "serving_prefill"
        return jit.StaticFunction(prefill_fn, observe=[self.model],
                                  warmup=False, dy2static=False)

    def _prefill(self, req: Request) -> Optional[RequestOutput]:
        t0 = time.perf_counter()
        s = int(req.prompt.size)
        bucket = _bucket(s, self.max_model_len)
        prog = self._prefill_progs.get(bucket)
        if prog is None:
            prog = self._prefill_progs[bucket] = self._make_prefill(bucket)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :s] = req.prompt
        n_kv, hd = self.pool.n_kv_heads, self.pool.head_dim
        flat = [Tensor(jnp.zeros((1, bucket, n_kv, hd), self.pool.dtype),
                       stop_gradient=True)
                for _ in range(2 * self.n_layers)]
        res = prog(Tensor(jnp.asarray(ids)),
                   Tensor(jnp.asarray(s - 1, jnp.int32)), *flat)
        last, flat_kv = res[0], res[1:]

        self.pool.allocate(req.req_id, s,
                           max_total_tokens=req.max_total_tokens)
        self.pool.write_prompt_kv(req.req_id, [
            (flat_kv[2 * i]._value[0, :s], flat_kv[2 * i + 1]._value[0, :s])
            for i in range(self.n_layers)])

        key = jax.random.PRNGKey(req.seed)
        key, sub = jax.random.split(key)
        tok = int(np.asarray(self._sample_one(last._value, req.temperature,
                                              sub)))
        state = _SeqState(req, pos=s, last_token=tok, key=key)
        now = time.perf_counter()
        self._m_prefill.observe(now - t0)
        self._m_ttft.observe(now - req.arrival_t)  # first token is OUT
        self._m_tokens.inc()
        self.stats["generated_tokens"] += 1
        if req.stream_cb is not None:
            req.stream_cb(req.req_id, tok, False)
        return self._maybe_retire(state, slot=None)

    def _sample_one(self, last, temperature, key):
        """First-token sample after prefill — delegates to the model's
        ``GenerationMixin._sample`` so there is exactly one copy of the
        greedy/temperature logic to keep token-identical with dense
        ``generate()``."""
        return self.model._sample(last, temperature, 0, key)[0]

    # -------------------------------------------------------------- decode
    def _make_decode(self) -> jit.StaticFunction:
        trunk, model, n_layers = self.trunk, self.model, self.n_layers

        def step_fn(tok, pos, temps, key, bt, *flat_pools):
            caches = [(flat_pools[2 * i], flat_pools[2 * i + 1])
                      for i in range(n_layers)]
            with no_grad():
                hidden, ncs = trunk.forward_paged(tok, pos, bt, caches)
                logits = model.logits(hidden)
            last = apply_op(lambda lv: lv[:, -1, :].astype(jnp.float32),
                            [ensure_tensor(logits)], name="last_logits")

            def batched_sample(lv, tv, kv):
                greedy = jnp.argmax(lv, axis=-1).astype(jnp.int32)
                t = jnp.maximum(tv.astype(jnp.float32), 1e-6)
                sampled = jax.random.categorical(
                    kv, lv / t[:, None], axis=-1).astype(jnp.int32)
                return jnp.where(tv > 0, sampled, greedy)

            nxt = apply_op(batched_sample,
                           [last, ensure_tensor(temps), ensure_tensor(key)],
                           name="serve_sample")
            flat = [t for c in ncs for t in c]
            return (nxt, *flat)

        # "decode compiles exactly once" becomes monitorable:
        # jit_compiles_total{fn="serving_decode"} must pin at 1
        step_fn.__name__ = "serving_decode"
        return jit.StaticFunction(step_fn, observe=[self.model],
                                  warmup=False, dy2static=False)

    def _decode_once(self) -> List[RequestOutput]:
        t0 = time.perf_counter()
        if self._decode_prog is None:
            self._decode_prog = self._make_decode()
        B = self.max_batch_slots
        tok = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        seq_ids: List[Optional[object]] = [None] * B
        for i, st in enumerate(self.slots):
            if st is None:
                continue
            # room for this step's KV write at position st.pos
            self.pool.append_token(st.req.req_id)
            tok[i, 0] = st.last_token
            pos[i] = st.pos
            temps[i] = st.req.temperature
            seq_ids[i] = st.req.req_id
        bt = self.pool.block_table_array(seq_ids, self.pages_per_seq)
        self._rng, sub = jax.random.split(self._rng)
        res = self._decode_prog(
            Tensor(jnp.asarray(tok)), Tensor(jnp.asarray(pos)),
            Tensor(jnp.asarray(temps)), Tensor(sub),
            Tensor(jnp.asarray(bt)),
            *[p for i in range(self.n_layers)
              for p in (self.pool.k_pools[i], self.pool.v_pools[i])])
        nxt, flat = res[0], res[1:]
        self.pool.set_arrays([flat[2 * i] for i in range(self.n_layers)],
                             [flat[2 * i + 1] for i in range(self.n_layers)])
        nxt_host = np.asarray(nxt.numpy()).reshape(B)
        now = time.perf_counter()
        self._m_decode.observe(now - t0)

        finished: List[RequestOutput] = []
        for i, st in enumerate(self.slots):
            if st is None:
                continue
            t = int(nxt_host[i])
            st.pos += 1
            st.last_token = t
            st.gen.append(t)
            # per-sequence inter-token latency: the streaming SLO — decode
            # step time plus any step this sequence sat through
            self._m_itl.observe(now - st.t_last)
            st.t_last = now
            self._m_tokens.inc()
            self.stats["generated_tokens"] += 1
            if st.req.stream_cb is not None:
                st.req.stream_cb(st.req.req_id, t, False)
            out = self._maybe_retire(st, slot=i)
            if out is not None:
                finished.append(out)
        return finished

    # -------------------------------------------------------------- retire
    def _maybe_retire(self, st: _SeqState,
                      slot: Optional[int]) -> Optional[RequestOutput]:
        req = st.req
        hit_eos = (req.eos_token_id is not None
                   and st.last_token == req.eos_token_id)
        if not hit_eos and len(st.gen) < req.max_new_tokens:
            if slot is None:  # fresh prefill: park in a free slot
                i = self.slots.index(None)
                self.slots[i] = st
            return None
        # retire NOW: pages go back to the pool this very step
        self.pool.free(req.req_id)
        if slot is not None:
            self.slots[slot] = None
        self._m_requests.labels(event="retired").inc()
        self.stats["finished_requests"] += 1
        out = RequestOutput(req_id=req.req_id,
                            prompt_token_ids=req.prompt,
                            token_ids=list(st.gen),
                            finish_reason="stop" if hit_eos else "length")
        if req.stream_cb is not None:
            # terminal call: `finished` is the reason string (truthy, so
            # bool-style `if finished:` consumers keep working)
            req.stream_cb(req.req_id, None, out.finish_reason)
        return out
