"""Overload as a first-class failure mode (ISSUE 19 tentpole).

Every prior robustness layer hardens against *component* failure —
engine kills, NaNs, lock bugs. Overload is different: the fleet used to
queue work it could never serve in time, then miss every deadline at
once. This module makes the stack shed and degrade deterministically
instead of collapsing, built from three pieces:

- :class:`DrainEstimator` — ONE shared TTFT predictor (waiting depth x
  step-time EWMA, the PR 5 ``load_score`` inputs). It backs BOTH
  ``BackpressureError.retry_after_s`` and the admission gate, so the
  honesty of the retry hint and the shed decision can never drift
  apart (tests pin the agreement).
- :class:`OverloadController` — deadline-aware admission (doomed work
  never enters the queue; shed with an honest ``retry_after_s``) plus a
  **brownout ladder**: under sustained backlog pressure it steps
  through reversible degradation levels — pause speculative drafts,
  cap the batch-tier chunk budget, preempt batch-tier decode slots
  (journal + requeue, the in-flight-migration move turned inward, so
  their slots and pages go to interactive work), restrict admission to
  interactive — and walks back
  down in reverse when pressure clears. Hysteresis mirrors the
  autoscaler idiom (hot/cold consecutive-step counters + cooldown): a
  signal oscillating inside the band never moves the ladder.
- :class:`RetryBudget` — a per-model token bucket the router consults
  before requeue/migration, so failover storms during an incident
  cannot amplify load. Exhausted budget fails fast (``"unavailable"``),
  never a retry loop.

Sacred invariants, held by construction: every brownout action is
data/host-side (compile surface stays ``step == step_buckets``; there
is no program the ladder can add), admitted streams stay bit-identical
to an unloaded run (brownout changes WHEN tokens are computed, never
WHAT — tokens are keyed by ``fold_in(seed, position)``), and shed /
expired outcomes extend the exactly-once ledger instead of escaping it.

The controller is a passive observer like the autoscaler: call
:meth:`OverloadController.observe` once per ``router.step()`` sweep.
Engines consult the attached controller at admission and inside
``_step_once`` planning; detaching it restores stock behavior.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import metrics
from . import router as _router_mod
from .scheduler import BackpressureError

__all__ = [
    "AdmissionShedError",
    "DrainEstimator",
    "LEVELS",
    "OverloadConfig",
    "OverloadController",
    "RetryBudget",
]

# the brownout ladder, mildest first — level N applies actions 1..N
LEVELS = (
    "normal",            # 0: no degradation
    "drafts-paused",     # 1: speculative drafts leftover -> 0
    "chunks-capped",     # 2: batch-tier prefill chunk budget shrunk
    "batch-parked",      # 3: batch decode slots preempted (journal+requeue)
    "interactive-only",  # 4: admission restricted to interactive tier
)

# every decision observe() can return — pre-created as counter label
# children so dashboards see explicit zeros (mirrors the autoscaler)
DECISIONS = ("steady", "escalate", "de-escalate", "cooldown")

# shed causes, pre-created the same way
SHED_CAUSES = ("deadline", "brownout")


class AdmissionShedError(BackpressureError):
    """Raised at submit when the overload controller refuses a request.

    Subclasses :class:`BackpressureError` so existing catch sites keep
    working; ``retry_after_s`` carries the SAME prediction that caused
    the shed (one estimator, one truth). ``cause`` is ``"deadline"``
    (predicted TTFT exceeds the request's deadline) or ``"brownout"``
    (ladder at interactive-only and the request is a lower tier)."""

    def __init__(self, message: str, retry_after_s: float,
                 queue_depth: int, cause: str):
        super().__init__(message, retry_after_s, queue_depth)
        self.cause = cause


class DrainEstimator:
    """The one shared queue-drain / TTFT predictor.

    ``predict_wait_s(depth, avg_step_s)`` estimates how long a request
    arriving NOW waits before first service: every queued request ahead
    of it costs about one step-time EWMA to clear. The same number is
    the honest ``retry_after_s`` hint — "come back when the backlog you
    would sit behind has drained"."""

    def __init__(self, floor_s: float = 0.05):
        if floor_s <= 0.0:
            raise ValueError("floor_s must be > 0")
        self.floor_s = float(floor_s)

    def predict_wait_s(self, queue_depth: int, avg_step_s: float) -> float:
        return max(self.floor_s, float(queue_depth) * float(avg_step_s))

    def for_engine(self, engine) -> float:
        """Prediction from a live engine's own signal surface."""
        return self.predict_wait_s(engine.scheduler.queue_depth,
                                   engine.avg_step_s)


class RetryBudget:
    """Per-model token bucket gating router requeue/migration retries.

    Every failover placement (requeue of waiting work, migration of
    in-flight work off a dead engine) spends one token from the model's
    bucket; :meth:`refill` restores ``refill_per_step`` tokens per
    router sweep up to ``capacity``. During steady operation the bucket
    is full and failover is free; during an incident storm the bucket
    empties and further retries fail fast to ``"unavailable"`` instead
    of amplifying load with re-dispatch churn."""

    def __init__(self, capacity: float = 32.0, refill_per_step: float = 1.0):
        if capacity <= 0.0:
            raise ValueError("capacity must be > 0")
        if refill_per_step < 0.0:
            raise ValueError("refill_per_step must be >= 0")
        self.capacity = float(capacity)
        self.refill_per_step = float(refill_per_step)
        self._tokens: Dict[str, float] = {}

    def tokens(self, model_id: str) -> float:
        return self._tokens.get(model_id, self.capacity)

    def try_take(self, model_id: str) -> bool:
        """Spend one token; False (and no spend) when the bucket is dry."""
        have = self._tokens.get(model_id, self.capacity)
        if have < 1.0:
            return False
        self._tokens[model_id] = have - 1.0
        return True

    def refill(self) -> None:
        """One router sweep's worth of budget back, every model."""
        for mid, have in list(self._tokens.items()):
            self._tokens[mid] = min(self.capacity,
                                    have + self.refill_per_step)


@dataclass(frozen=True)
class OverloadConfig:
    """Brownout policy knobs. The pressure signal is the worst healthy
    engine's predicted queue-drain time in seconds (the same
    :class:`DrainEstimator` number used for admission);
    ``hot_backlog_s`` must sit strictly above ``cold_backlog_s`` — the
    hysteresis band a noisy signal parks inside."""

    hot_backlog_s: float = 1.0       # worst-engine backlog above -> hot
    cold_backlog_s: float = 0.25     # worst-engine backlog below -> cold
    hot_steps: int = 2               # consecutive hot obs to escalate
    cold_steps: int = 4              # consecutive cold obs to de-escalate
    cooldown_steps: int = 4          # observations between transitions
    max_level: int = len(LEVELS) - 1
    floor_s: float = 0.05            # DrainEstimator floor
    batch_chunk_cap: int = 4         # prefill chunk cap at chunks-capped
    interactive_priority: int = 0    # priority admitted at interactive-only
    batch_priority: int = 2          # priority parked at batch-parked
    deadline_slack: float = 1.0      # shed when predicted > slack * deadline

    def __post_init__(self):
        if self.hot_backlog_s <= self.cold_backlog_s:
            raise ValueError(
                "hot_backlog_s must be strictly greater than "
                "cold_backlog_s (the hysteresis band)")
        if self.hot_steps < 1 or self.cold_steps < 1:
            raise ValueError("hot_steps and cold_steps must be >= 1")
        if self.cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")
        if not 1 <= self.max_level <= len(LEVELS) - 1:
            raise ValueError(
                f"max_level must be in [1, {len(LEVELS) - 1}]")
        if self.batch_chunk_cap < 1:
            raise ValueError("batch_chunk_cap must be >= 1")
        if self.deadline_slack <= 0.0:
            raise ValueError("deadline_slack must be > 0")


class OverloadController:
    """Deadline-aware admission + the brownout ladder (module docstring
    has the policy)::

        ctl = OverloadController(router)
        while router.has_work:
            router.step()
            ctl.observe()

    ``observe()`` returns the decision string it counted (one of
    ``DECISIONS``) so drivers and tests can assert the trajectory, and
    (re-)attaches the controller to every current engine handle — an
    autoscaler-spawned newcomer is governed from the next sweep."""

    def __init__(self, router, model: Optional[str] = None,
                 config: Optional[OverloadConfig] = None):
        self._router = router
        self._model = router._resolve_model(model)
        self.config = config or OverloadConfig()
        self.estimator = DrainEstimator(floor_s=self.config.floor_s)
        self.level = 0
        self._hot = 0                    # consecutive hot observations
        self._cold = 0                   # consecutive cold observations
        self._cooldown = 0               # observations left to sit out
        self.events: List[Tuple[str, int]] = []   # (decision, new level)
        reg = metrics.get_registry()
        self._m_level = reg.gauge(
            "paddle_tpu_overload_brownout_level",
            "Current brownout ladder level (0 = normal, "
            f"{len(LEVELS) - 1} = interactive-only)",
            labels=("model_id",))
        self._m_transitions = reg.counter(
            "paddle_tpu_overload_transitions_total",
            "Brownout ladder level transitions by direction",
            labels=("model_id", "direction"))
        self._m_decisions = reg.counter(
            "paddle_tpu_overload_decisions_total",
            "observe() outcomes by decision",
            labels=("model_id", "decision"))
        self._m_shed = reg.counter(
            "paddle_tpu_overload_shed_total",
            "Requests refused at admission by the overload controller",
            labels=("model_id", "cause"))
        self._m_signal = reg.gauge(
            "paddle_tpu_overload_backlog_seconds",
            "Worst healthy engine's predicted queue-drain time — the "
            "brownout pressure signal", labels=("model_id",))
        for d in ("up", "down"):
            self._m_transitions.labels(model_id=self._model, direction=d)
        for d in DECISIONS:
            self._m_decisions.labels(model_id=self._model, decision=d)
        for c in SHED_CAUSES:
            self._m_shed.labels(model_id=self._model, cause=c)
        self._m_level.labels(model_id=self._model).set(0)
        self.attach()

    # ---------------------------------------------------------- attachment
    def attach(self) -> None:
        """Point every current engine of the governed model at this
        controller. Idempotent; re-run each observe() so engines the
        autoscaler spawns later are governed too."""
        for h in self._router.handles(self._model):
            try:
                h.engine._overload = self
            except Exception:
                pass  # dead/unreadable engine: the router owns it

    def detach(self) -> None:
        """Restore stock behavior on every engine (tests use this)."""
        for h in self._router.handles(self._model):
            try:
                if getattr(h.engine, "_overload", None) is self:
                    h.engine._overload = None
            except Exception:
                pass

    # ------------------------------------------------------------- signals
    def signal(self) -> float:
        """Worst healthy engine's predicted queue-drain seconds. The
        MAX (not mean) because brownout protects the tail: one swamped
        engine missing every interactive deadline is an incident even
        if its siblings are idle."""
        healthy = [h for h in self._router.handles(self._model)
                   if h.state == _router_mod.HEALTHY]
        worst = 0.0
        for h in healthy:
            try:
                worst = max(worst, self.estimator.for_engine(h.engine))
            except Exception:
                pass  # unreadable engine: the router's health gate owns it
        self._m_signal.labels(model_id=self._model).set(worst)
        return worst

    # ----------------------------------------------------- engine-side API
    # Engines call these from add_request / _step_once; every answer is
    # host-side data (chunk sizes, draft gating, park decisions) so the
    # compile surface cannot change.
    @property
    def drafts_paused(self) -> bool:
        return self.level >= 1

    def chunk_cap(self) -> Optional[int]:
        """Batch-tier prefill chunk cap, or None when not capping."""
        return self.config.batch_chunk_cap if self.level >= 2 else None

    @property
    def park_batch(self) -> bool:
        return self.level >= 3

    @property
    def interactive_only(self) -> bool:
        return self.level >= 4

    def admit_priority_cap(self) -> Optional[int]:
        """Admission hold for ``FCFSScheduler.admit``: at
        ``batch-parked`` the batch tier stays queued (admitting it
        would only hand back the slots preemption just freed — an
        admit/preempt ping-pong); at ``interactive-only`` everything
        above the interactive priority holds. ``None`` = no hold."""
        if self.level >= 4:
            return self.config.interactive_priority
        if self.level >= 3:
            return self.config.batch_priority - 1
        return None

    def preempt_priority_cut(self) -> Optional[int]:
        """Lowest priority value the engine should PREEMPT (journal +
        requeue) out of its decode slots, or ``None`` when not
        preempting. At ``batch-parked`` only the batch tier is evicted;
        at ``interactive-only`` every non-interactive tier is — an
        admission hold alone cannot help the premium tier while
        already-running standard streams sit on the slots for their
        whole decode."""
        if self.level >= 4:
            return self.config.interactive_priority + 1
        if self.level >= 3:
            return self.config.batch_priority
        return None

    def admission_check(self, engine, req) -> None:
        """Gate one request at submit time; raises
        :class:`AdmissionShedError` to shed. Runs BEFORE the request
        enters the queue, so shed work never holds pages or slots."""
        cfg = self.config
        predicted = self.estimator.for_engine(engine)
        if self.interactive_only and req.priority > cfg.interactive_priority:
            self._shed(engine, req, predicted, "brownout")
        if req.deadline_s is not None and \
                predicted > cfg.deadline_slack * req.deadline_s:
            self._shed(engine, req, predicted, "deadline")

    def _shed(self, engine, req, predicted: float, cause: str) -> None:
        self._m_shed.labels(model_id=self._model, cause=cause).inc()
        engine._trace.emit("req.shed", req.req_id,
                           arg=predicted, label=cause)
        raise AdmissionShedError(
            f"request {req.req_id} shed at admission ({cause}): "
            f"predicted wait {predicted:.3f}s",
            retry_after_s=predicted,
            queue_depth=engine.scheduler.queue_depth,
            cause=cause)

    # -------------------------------------------------------------- control
    def observe(self) -> str:
        """One control tick: read the signal, update hysteresis
        counters, maybe move the ladder. Call once per ``router.step()``
        sweep (after it, like the autoscaler)."""
        self.attach()
        decision = self._decide()
        self._m_decisions.labels(model_id=self._model,
                                 decision=decision).inc()
        if decision in ("escalate", "de-escalate"):
            self.events.append((decision, self.level))
        return decision

    def _decide(self) -> str:
        cfg = self.config
        sig = self.signal()
        hot = sig > cfg.hot_backlog_s
        cold = sig < cfg.cold_backlog_s
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0

        if self._cooldown > 0:
            self._cooldown -= 1
            return "cooldown"

        if self._hot >= cfg.hot_steps and self.level < cfg.max_level:
            self._move(+1)
            return "escalate"
        if self._cold >= cfg.cold_steps and self.level > 0:
            self._move(-1)
            return "de-escalate"
        return "steady"

    def _move(self, delta: int) -> None:
        self.level += delta
        direction = "up" if delta > 0 else "down"
        self._m_transitions.labels(model_id=self._model,
                                   direction=direction).inc()
        self._m_level.labels(model_id=self._model).set(self.level)
        self._emit_level()
        self._cooldown = self.config.cooldown_steps
        self._hot = 0
        self._cold = 0

    def _emit_level(self) -> None:
        """Trace the transition on every governed engine's stream (the
        model id rides as req_id, mirroring the step.* idiom)."""
        for h in self._router.handles(self._model):
            try:
                h.engine._trace.emit("brownout.level", self._model,
                                     arg=self.level,
                                     label=LEVELS[self.level])
            except Exception:
                pass
