"""Fleet-scale serving control plane: the layer that turns N engines into
one serving product (ROADMAP item 4).

``Router`` grows the old round-robin ``EnginePool`` into a real front
door over a fleet of :class:`~.engine.ServingEngine` replicas:

- **Least-loaded dispatch** — admission picks the healthy engine with the
  minimum ``load_score()`` ((queued + running) x step-time EWMA, the same
  EWMA behind ``BackpressureError.retry_after_s``); exact ties break
  round-robin so idle fleets still rotate. Every placement lands in
  ``paddle_tpu_router_dispatch_total{engine_id,model_id}``.

- **Health gating + auto-drain** — each engine carries a state
  (``healthy`` / ``degraded`` / ``draining`` / ``down``). The router
  derives ``degraded`` from the engine's PR 3 watchdog (``health()``)
  at every :meth:`step`; a non-healthy engine stops receiving admissions,
  keeps stepping so its in-flight work finishes (or falls to the existing
  ``cancel``/deadline machinery), and its WAITING requests are requeued
  onto healthy siblings **exactly once**: a request is moved at most one
  time, and if no healthy engine can adopt it (none exists, bounded
  queue full, or it was already moved once) it retires deterministically
  with ``finish_reason="unavailable"`` — no duplicates, no silent drops.

- **Crash containment + in-flight migration** — an exception escaping
  one engine's ``step()`` marks THAT engine ``down``
  (``paddle_tpu_router_engine_crash_total{engine_id,model_id}``) instead
  of killing the serving loop, and everything it held moves: waiting
  requests requeue as above, and IN-FLIGHT requests migrate
  (``paddle_tpu_router_migrated_total``) under the same move-once
  discipline — the engine's per-request token journals
  (``export_inflight``) carry (prompt, generated tokens, sampling
  params, deadline, stream position) to a healthy sibling, which
  re-prefills prompt + journal and continues decoding
  **token-identically** (sampling is a pure function of request seed and
  stream position — engine.py's determinism contract), resuming stream
  emission at the journaled seq so clients see no duplicated or missing
  chunk. :meth:`mark_down` takes the same path. Unplaceable in-flight
  work retires ``"unavailable"`` delivering the tokens generated so far.

- **Rolling weight reload** — :meth:`reload` drains one engine at a time
  (admissions gate out; its in-flight and queued work finishes locally
  while siblings keep serving), restores the newest committed PR 4
  checkpoint
  into it (checksum-verified via ``CheckpointManager.restore``; weights
  land IN-PLACE via ``set_state_dict`` so the compiled decode step picks
  them up without recompiling — ``paddle_tpu_jit_compiles_total`` stays
  at one decode compile per engine across a weight push), re-warms it
  with a canary request, and returns it to rotation. A canary that comes
  back ``nan``/``error`` marks the engine ``down`` instead of serving a
  bad checkpoint.

- **Multi-model tenancy** — the router owns a ``{model_id: [engines]}``
  table; :meth:`select`/:meth:`submit` route by model id and unknown ids
  raise an actionable ValueError naming the served models
  (``CompletionAPI(router)`` forwards its ``model=`` field here).

- **Runtime topology** — :meth:`add_engine` stamps out one more replica
  from the model's ``add_model`` construction spec (monotone, never
  reused engine ids; a warm persistent compile cache makes the spawn
  zero-fresh-compile) and :meth:`remove_engine` retires an engine that
  is already gated out and empty — the drain-then-remove pair
  ``paddle_tpu.loadgen``'s queue-depth autoscaler closes its loop on.

Threading contract: dispatch/step/run/reload are single-threaded like the
engines they drive (one driver thread owns the control plane);
:meth:`health` is safe to call from a scrape thread, which is how
``MetricsServer(health_cb=router.health)`` serves ``/healthz`` (503 only
when some served model has NO healthy engine) and
``/healthz?engine=<id>`` (one engine's view).

State machine (docs/SERVING.md "Control plane" has the diagram)::

    healthy --watchdog trip--> degraded --recovery steps--> healthy
    healthy --drain()/reload--> draining --reload ok/undrain--> healthy
    any --mark_down()/step crash/failed canary--> down --undrain()--> healthy

Degraded/draining/down engines never receive admissions; degraded and
draining engines still step (they recover or finish); down engines are
emptied (waiting requeued, in-flight migrated, each exactly once) and
skipped.
"""
from __future__ import annotations

import signal as _signal
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import faults, metrics
from . import tracing
from .engine import ServingEngine
from .grammar import GrammarFSM, toy_tokenizer
from .scheduler import Request, RequestOutput
from .wal import RequestWAL, WalRequest

__all__ = ["Router", "EngineHandle", "NoHealthyEngineError",
           "HEALTHY", "DEGRADED", "DRAINING", "DOWN"]

HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"
DOWN = "down"

# numeric encoding for the per-engine state gauge (docs/OBSERVABILITY.md):
# alerts key on  > 0  (any engine out of rotation)
_STATE_CODE = {HEALTHY: 0.0, DEGRADED: 1.0, DRAINING: 2.0, DOWN: 3.0}

faults.declare_point(
    "router.engine_step", "wrapping ONE engine's step() inside "
    "router.step() — a raise here simulates that engine dying mid-decode; "
    "the router must contain it (mark down, migrate its in-flight work) "
    "and never let it escape the fleet loop")


class NoHealthyEngineError(RuntimeError):
    """Every engine serving the requested model is out of rotation
    (degraded/draining/down) — the 503 analogue of BackpressureError's
    429. The fleet is known-unable to admit right now; retry after the
    watchdog recovers or the drain/reload finishes."""


class EngineHandle:
    """One engine's seat in the router: identity, gate state, and the
    weight version it serves."""

    __slots__ = ("engine", "engine_id", "model_id", "state", "weights_step",
                 "last_error")

    def __init__(self, engine: ServingEngine, engine_id: str,
                 model_id: str):
        self.engine = engine
        self.engine_id = engine_id
        self.model_id = model_id
        self.state = HEALTHY
        self.weights_step: Optional[int] = None  # last reload's ckpt step
        self.last_error: Optional[str] = None    # repr of a step() crash


class Router:
    """Control plane over a fleet of engines (see module docstring).

    ::

        router = Router()
        router.add_model("llama", model, replicas=2, page_size=16)
        rid = router.submit(prompt_ids, model="llama", max_new_tokens=32)
        outputs = router.run()               # least-loaded, health-gated
        router.reload(ckpt_dir)              # rolling weight push

    ``add_model`` accepts one model (weights shared by every replica —
    jax arrays are immutable, so sharing is free) or a sequence of model
    instances (one per replica — what :meth:`reload` needs for true
    rolling version isolation: with a shared model every replica flips to
    the new weights at the first restore)."""

    def __init__(self, retry_budget=None, wal_dir: Optional[str] = None,
                 wal_segment_bytes: int = 1 << 20):
        """``retry_budget`` (an :class:`~.overload.RetryBudget`) gates
        failover requeue/migration placements per model so an incident
        storm can't amplify load — each placement spends one token,
        :meth:`step` refills, and a dry bucket retires the request
        ``"unavailable"`` immediately (fail fast, never a retry loop).
        None (the default) keeps retries unmetered.

        ``wal_dir`` opts the router into DURABILITY (serving/wal.py,
        docs/RESILIENCE.md "Durability"): every :meth:`submit` journals
        an admission record, every step's committed tokens journal as a
        progress record, and retirement is journaled — group-committed
        with ONE fsync per :meth:`step`. Stream chunks are released to
        client callbacks only AFTER the commit barrier (commit-then-
        emit), so a client can never have seen a token the log could
        lose; after a process death, :meth:`recover` on a fresh router
        pointed at the same directory re-admits every unfinished
        request and the streams complete bit-identical, chunks
        exactly-once. None (the default) keeps the old purely
        in-memory behavior."""
        self._retry_budget = retry_budget
        self._wal = (None if wal_dir is None else
                     RequestWAL(wal_dir, segment_bytes=wal_segment_bytes))
        self._wal_ids: Dict[object, int] = {}    # req_id -> live wal_id
        self._wal_cursor: Dict[int, int] = {}    # wal_id -> committed toks
        self._wal_alias: Dict[int, int] = {}     # superseded -> successor
        self._client_cbs: Dict[int, Callable] = {}
        self._chunk_buf: List[tuple] = []        # awaiting the commit
        self._stream_hist: Dict[int, List[tuple]] = {}
        self._models: Dict[str, List[EngineHandle]] = {}
        self._handles: Dict[str, EngineHandle] = {}
        self._rr: Dict[str, int] = {}          # per-model tie-break cursor
        # per-model construction spec (shared model ref + engine kwargs)
        # so add_engine() can stamp out identical replicas at runtime,
        # and a monotone id cursor so engine ids are NEVER reused across
        # a remove/add cycle (metrics label children and journals keyed
        # by engine_id must stay unambiguous)
        self._specs: Dict[str, tuple] = {}
        self._next_idx: Dict[str, int] = {}
        self._lock = threading.Lock()  # tpulint: lock=router (rr cursors + state flips)
        self._requeued: set = set()            # req_ids moved once already
        self._stash: Dict[object, RequestOutput] = {}
        # fleet tracer + flight recorder (tracing.py): dispatch/requeue/
        # migrate land in the same journal the engines write, and the
        # recorder auto-dumps on crash containment and on the aggregate
        # /healthz ok→degraded transition
        self._trace = tracing.get_tracer()
        self._last_health_ok = True
        reg = metrics.get_registry()
        self._m_dispatch = reg.counter(
            "paddle_tpu_router_dispatch_total",
            "Requests placed on an engine by the router's least-loaded "
            "dispatch", labels=("engine_id", "model_id"))
        self._m_requeued = reg.counter(
            "paddle_tpu_router_requeued_total",
            "Waiting requests moved off a non-healthy engine onto a "
            "healthy sibling (each request moves at most once)")
        self._m_unplaceable = reg.counter(
            "paddle_tpu_router_unplaceable_total",
            "Requests (waiting or in-flight) the router could not place "
            "on a sibling (no healthy engine / bounded queue full / "
            "already moved once) — retired with "
            "finish_reason=\"unavailable\"")
        self._m_migrated = reg.counter(
            "paddle_tpu_router_migrated_total",
            "IN-FLIGHT requests moved off a dead engine onto a healthy "
            "sibling via their token journals (each request moves at "
            "most once; the continued stream is token-identical)")
        self._m_crash = reg.counter(
            "paddle_tpu_router_engine_crash_total",
            "Exceptions escaping one engine's step() that the router "
            "contained by marking the engine down and migrating its work",
            labels=("engine_id", "model_id"))
        self._m_reloads = reg.counter(
            "paddle_tpu_router_reloads_total",
            "Per-engine rolling weight reloads by result",
            labels=("result",))
        for r in ("ok", "error"):
            self._m_reloads.labels(result=r)   # pre-create: scrapes show 0
        self._m_adapter_loads = reg.counter(
            "paddle_tpu_serving_adapter_loads_total",
            "Fleet-wide LoRA adapter hot-loads via "
            "Router.register_adapter, by per-engine result (a canary "
            "failure rolls that engine's install back)",
            labels=("result",))
        for r in ("ok", "error"):
            self._m_adapter_loads.labels(result=r)
        self._m_state = reg.gauge(
            "paddle_tpu_router_engine_state",
            "Router gate state per engine: 0 healthy, 1 degraded, "
            "2 draining, 3 down", labels=("engine_id", "model_id"))
        self._m_budget_exhausted = reg.counter(
            "paddle_tpu_router_retry_budget_exhausted_total",
            "Failover placements refused because the model's retry "
            "budget was dry (the request retired \"unavailable\" "
            "instead of joining a requeue/migration storm)",
            labels=("model_id",))
        self._m_recovered = reg.counter(
            "paddle_tpu_wal_recovered_requests_total",
            "Requests Router.recover() replayed out of the WAL after a "
            "process restart, by outcome: resumed (re-admitted via the "
            "journaled re-prefill path), completed (journal already "
            "terminal — only the retire record was torn away), expired "
            "(deadline lapsed across the death), failed (no engine "
            "could adopt it)", labels=("outcome",))
        for oc in ("resumed", "completed", "expired", "failed"):
            self._m_recovered.labels(outcome=oc)

    # ------------------------------------------------------------- topology
    def add_model(self, model_id: str, model, replicas: int = 1,
                  **engine_kwargs) -> List[str]:
        """Register ``replicas`` engines serving ``model`` under
        ``model_id``; returns the assigned engine ids
        (``"<model_id>/<n>"`` — stable, unlike the process-wide default).
        ``model`` may be a sequence of model instances (one per replica,
        ``replicas`` then defaults to its length) for per-replica weight
        isolation under :meth:`reload`."""
        model_id = str(model_id)
        if model_id in self._models:
            raise ValueError(
                f"model id {model_id!r} already registered "
                f"({len(self._models[model_id])} engines); model ids are "
                f"immutable — pick a new id for a new fleet")
        if isinstance(model, (list, tuple)):
            models = list(model)
            if not models:
                raise ValueError("empty model sequence")
            replicas = len(models)
        else:
            models = [model] * int(replicas)
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        handles = []
        for i, m in enumerate(models):
            eid = f"{model_id}/{i}"
            eng = ServingEngine(m, engine_id=eid, model_id=model_id,
                                **engine_kwargs)
            handles.append(EngineHandle(eng, eid, model_id))
        with self._lock:
            self._models[model_id] = handles
            for h in handles:
                self._handles[h.engine_id] = h
                self._set_state_gauge(h)
            self._rr.setdefault(model_id, 0)
            self._specs[model_id] = (models[0], dict(engine_kwargs))
            self._next_idx[model_id] = len(models)
        return [h.engine_id for h in handles]

    def add_engine(self, model_id: Optional[str] = None, model=None,
                   **engine_overrides) -> str:
        """Spawn ONE more engine for an already-registered model at
        runtime — the autoscaler's scale-up primitive. The new replica
        reuses the ``add_model`` construction spec (shared model ref —
        jax arrays are immutable, so weight sharing is free — plus the
        original ``engine_kwargs``, including ``compile_cache_dir``: a
        warm persistent compile cache means the newcomer materializes
        its step programs from disk with ZERO fresh compiles);
        ``model=`` / keyword overrides replace pieces of the spec. The
        engine id is ``"<model_id>/<n>"`` with a monotone ``n`` that is
        never reused after :meth:`remove_engine`, and the replica
        enters rotation ``healthy`` immediately."""
        mid = self._resolve_model(model_id)
        base_model, kwargs = self._specs[mid]
        kwargs = dict(kwargs)
        kwargs.update(engine_overrides)
        with self._lock:
            idx = self._next_idx[mid]
            self._next_idx[mid] = idx + 1
        eid = f"{mid}/{idx}"
        eng = ServingEngine(base_model if model is None else model,
                            engine_id=eid, model_id=mid, **kwargs)
        h = EngineHandle(eng, eid, mid)
        with self._lock:
            self._models[mid].append(h)
            self._handles[eid] = h
        self._set_state_gauge(h)
        return eid

    def remove_engine(self, engine_id: str) -> None:
        """Retire one engine from the fleet — the autoscaler's
        scale-down primitive, and deliberately the UNFORGIVING half of
        drain-then-remove: the engine must already be gated out of
        admission (``draining``/``down``, via :meth:`drain` or
        :meth:`mark_down`) and must hold no work (its in-flight
        requests finished locally while draining; a downed engine was
        evacuated), and it must not be the model's last engine. Any
        violation raises instead of dropping requests — callers that
        want best-effort shedding have ``mark_down`` + migration for
        that. The engine's state gauge lands on the ``down`` code (its
        label child outlives the handle; 3 reads as "out of rotation"
        on dashboards)."""
        h = self._require(engine_id)
        if h.state == HEALTHY:
            raise ValueError(
                f"engine {h.engine_id!r} is still healthy (admitting) — "
                f"drain({h.engine_id!r}) first, step until its work "
                f"finishes, then remove")
        if self._safe_has_work(h):
            raise ValueError(
                f"engine {h.engine_id!r} still has queued or in-flight "
                f"work — keep stepping the fleet until it drains")
        # scoop outputs the engine finished but nobody collected yet:
        # after the handle is gone take_outputs() can't reach them, and
        # exactly-once handout must survive any remove/collect ordering
        try:
            self._stash.update(h.engine.take_outputs())
        except Exception:
            pass
        with self._lock:
            if len(self._models[h.model_id]) <= 1:
                raise ValueError(
                    f"engine {h.engine_id!r} is the last engine of model "
                    f"{h.model_id!r} — a served model must keep at least "
                    f"one replica (use drain() to just gate it out)")
            self._models[h.model_id].remove(h)
            del self._handles[h.engine_id]
            h.state = DOWN
        self._set_state_gauge(h)

    @property
    def models(self) -> List[str]:
        return sorted(self._models)

    def engines(self, model: Optional[str] = None) -> List[ServingEngine]:
        """Engines of one model (router order) or the whole fleet."""
        if model is not None:
            return [h.engine for h in self._model_handles(model)]
        return [h.engine for h in self._handles.values()]

    def engine(self, engine_id: str) -> ServingEngine:
        return self._require(engine_id).engine

    def handles(self, model: Optional[str] = None) -> List[EngineHandle]:
        """Snapshot of one model's (or the whole fleet's) handles —
        (engine, id, state) triples for controllers that read topology
        without mutating it (the loadgen autoscaler's signal scan)."""
        if model is not None:
            mid = self._resolve_model(model)
            with self._lock:
                return list(self._models[mid])
        with self._lock:
            return list(self._handles.values())

    def states(self) -> Dict[str, str]:
        """{engine_id: state} snapshot of the whole fleet (safe from any
        thread: iterates a copy taken under the topology lock)."""
        with self._lock:
            handles = list(self._handles.values())
        return {h.engine_id: h.state for h in handles}

    def __len__(self) -> int:
        return len(self._handles)

    def _model_handles(self, model) -> List[EngineHandle]:
        mid = self._resolve_model(model)
        return self._models[mid]

    def _resolve_model(self, model) -> str:
        if model is None:
            if len(self._models) == 1:
                return next(iter(self._models))
            raise ValueError(
                f"model= is required when the router serves "
                f"{len(self._models)} models (serving: {self.models}); "
                f"pass one of them")
        mid = str(model)
        if mid not in self._models:
            # 4xx-style actionable rejection, same contract as
            # engine.check_request: name what was asked AND what exists
            raise ValueError(
                f"unknown model id {mid!r} (serving: {self.models}); "
                f"register it with router.add_model({mid!r}, model) or "
                f"request a served model")
        return mid

    def _set_state_gauge(self, h: EngineHandle) -> None:
        self._m_state.labels(engine_id=h.engine_id,
                             model_id=h.model_id).set(_STATE_CODE[h.state])

    # ------------------------------------------------------------- dispatch
    def select(self, model: Optional[str] = None,
               adapter_id: Optional[str] = None) -> EngineHandle:
        """Least-loaded healthy engine for ``model`` (the single served
        model when omitted): minimum ``engine.load_score()``; exact ties
        rotate round-robin. ``adapter_id`` narrows tenancy to
        ``(model_id, adapter_id)``: only engines whose AdapterStore
        holds the adapter are candidates (every engine holds ``None``).
        Raises ValueError for an unknown model and
        :class:`NoHealthyEngineError` when every engine of the model is
        gated out (or none holds the adapter)."""
        mid = self._resolve_model(model)
        cands = [h for h in self._models[mid] if h.state == HEALTHY]
        if adapter_id is not None:
            holders = [h for h in cands
                       if h.engine.adapters.holds(adapter_id)]
            if cands and not holders:
                raise NoHealthyEngineError(
                    f"no healthy engine for model {mid!r} holds adapter "
                    f"{adapter_id!r}; register_adapter() hot-loads it "
                    f"fleet-wide")
            cands = holders
        if not cands:
            states = {h.engine_id: h.state for h in self._models[mid]}
            raise NoHealthyEngineError(
                f"no healthy engine for model {mid!r} (states: {states}); "
                f"retry after recovery, or undrain()/reload a replica")
        scores = [h.engine.load_score() for h in cands]
        best = min(scores)
        tied = [h for h, s in zip(cands, scores) if s == best]
        with self._lock:
            pick = tied[self._rr[mid] % len(tied)]
            # modular, not unbounded (the same fix EnginePool.next got):
            # the cursor only breaks ties, so any stable modulus works
            self._rr[mid] = (self._rr[mid] + 1) % len(self._models[mid])
        return pick

    def submit(self, prompt, model: Optional[str] = None,
               **request_kwargs):
        """Route one request: least-loaded placement + dispatch counter.
        Returns the engine's ``req_id``; raises like
        ``ServingEngine.add_request`` (plus the routing errors of
        :meth:`select`). A request carrying ``adapter_id=`` routes only
        to engines holding that adapter. Drive the fleet with
        :meth:`run`."""
        h = self.select(model, adapter_id=request_kwargs.get("adapter_id"))
        if self._wal is not None:
            rid = self._submit_durable(h, prompt, request_kwargs)
        else:
            rid = h.engine.add_request(prompt, **request_kwargs)
        self._m_dispatch.labels(engine_id=h.engine_id,
                                model_id=h.model_id).inc()
        self._trace.emit("req.dispatch", rid, label=h.engine_id)
        return rid

    def _submit_durable(self, h: EngineHandle, prompt,
                        request_kwargs: dict):
        """WAL-armed admission: swap the client's ``stream_cb`` for the
        router's buffering wrapper (chunks release only after the next
        group commit — commit-then-emit) and journal the admission
        record. The record is framed AFTER ``add_request`` accepts (a
        backpressure-rejected request must not leave a forever-pending
        admit in the log) and becomes durable at the next
        :meth:`step`'s fsync — the group-commit window. The journaled
        fields come from the ACCEPTED Request object itself
        (``Request.wal_admission``), so engine-side defaulting and seed
        canonicalization can never drift from what recovery rebuilds."""
        wid = self._wal.new_id()
        kwargs = dict(request_kwargs)
        client_cb = kwargs.pop("stream_cb", None)
        kwargs["stream_cb"] = self._durable_cb(wid)
        rid = h.engine.add_request(prompt, **kwargs)
        req = next(r for r in h.engine.scheduler.waiting
                   if r.req_id == rid)
        self._wal.append("admit",
                         **req.wal_admission(wid, model=h.model_id))
        self._wal_ids[rid] = wid
        self._wal_cursor[wid] = 0
        if client_cb is not None:
            self._client_cbs[wid] = client_cb
        return rid

    def wal_id_of(self, req_id) -> Optional[int]:
        """The durable id journaled for a live request this process
        admitted (or recovered) — ``Request.req_id`` is a plain process-
        local counter and collides across restarts, so the WAL id is
        what a client must hold to :meth:`attach_stream` after a crash.
        None when the request is unknown or the router runs WAL-off."""
        return self._wal_ids.get(req_id)

    def _count_dispatch(self, h: EngineHandle) -> None:
        """Dispatch-accounting hook for front doors (CompletionAPI) that
        enqueue on a selected handle themselves."""
        self._m_dispatch.labels(engine_id=h.engine_id,
                                model_id=h.model_id).inc()

    # ----------------------------------------------------------- health gate
    def _refresh_health(self) -> None:
        """Derive degraded/healthy from each engine's watchdog and
        auto-drain the queue of anything that just left rotation. Manual
        states (draining/down) are sticky — only undrain()/reload flip
        them back. A health probe that RAISES (or returns garbage) is
        worse than degraded: contained like a step crash, so a broken
        engine can never kill the fleet loop through its own probe."""
        for h in list(self._handles.values()):
            if h.state in (DRAINING, DOWN):
                continue
            try:
                ok = h.engine.health()["status"] == "ok"
            except Exception as e:
                self._contain(h, e)
                continue
            if h.state == HEALTHY and not ok:
                with self._lock:
                    h.state = DEGRADED
                self._set_state_gauge(h)
                self._requeue_waiting(h)
            elif h.state == DEGRADED and ok:
                with self._lock:
                    h.state = HEALTHY
                self._set_state_gauge(h)

    def _requeue_waiting(self, h: EngineHandle) -> None:
        """Move ``h``'s WAITING requests onto healthy siblings, each
        exactly once; whatever cannot move retires
        ``finish_reason="unavailable"`` on ``h`` (delivered through the
        normal output path). In-flight slots stay: they finish on ``h``
        (still stepping while degraded/draining) or migrate when ``h``
        goes down (:meth:`_migrate_inflight`). If ``steal_queued``
        itself raises, the queue is scraped by hand — a broken METHOD
        must not silently drop requests whose state is readable."""
        try:
            stolen = h.engine.steal_queued()
        except Exception:
            stolen = self._scrape_queued(h)
        self._place_elsewhere(h, stolen, self._m_requeued)

    def _migrate_inflight(self, h: EngineHandle) -> None:
        """Move ``h``'s IN-FLIGHT requests onto healthy siblings via
        their token journals (``engine.export_inflight``), each exactly
        once under the same ``_requeued`` move-once discipline as
        waiting-requeue: the adoptive engine re-prefills prompt +
        journal and continues the stream token-identically, resuming
        emission at the journaled seq. Unplaceable requests retire
        ``"unavailable"`` delivering the tokens generated so far. If
        ``export_inflight`` itself raises, the journals are scraped by
        hand (they are plain host state)."""
        try:
            journals = h.engine.export_inflight()
        except Exception:
            journals = self._scrape_inflight(h)
        self._place_elsewhere(h, journals, self._m_migrated)

    def _scrape_inflight(self, h: EngineHandle) -> List[Request]:
        """Fallback when the INSTANCE's ``export_inflight`` attribute is
        broken (shadowed, wrapped, corrupted): invoke the CLASS
        implementation directly on the engine's host state — the
        journals are plain python lists, and losing a mid-stream request
        because a method binding is broken would violate
        never-silently-dropped. One copy of the journaling logic either
        way. Anything truly unreadable stays lost (nothing more exists
        to read)."""
        try:
            return ServingEngine.export_inflight(h.engine)
        except Exception:
            return []

    def _scrape_queued(self, h: EngineHandle) -> List[Request]:
        """``steal_queued`` fallback via the class implementation, same
        rationale as :meth:`_scrape_inflight`."""
        try:
            return ServingEngine.steal_queued(h.engine)
        except Exception:
            return []

    def _place_elsewhere(self, h: EngineHandle, reqs: Sequence[Request],
                         moved_counter) -> None:
        """The one placement loop behind requeue AND migration: move each
        request to a healthy sibling at most once; a request that cannot
        move (no healthy engine, target refused, already moved) retires
        ``"unavailable"`` — never dropped, never duplicated."""
        for req in reqs:
            if (self._retry_budget is not None
                    and not self._retry_budget.try_take(h.model_id)):
                # retry budget dry: an incident storm is re-dispatching
                # faster than the bucket refills — fail fast instead of
                # amplifying the overload with another placement
                self._m_budget_exhausted.labels(
                    model_id=h.model_id).inc()
                self._retire_unavailable(h, req)
                continue
            target: Optional[EngineHandle] = None
            if req.req_id not in self._requeued:
                try:
                    # tenancy-aware failover: a constrained/adapter
                    # request may only land on a sibling HOLDING its
                    # adapter — adopt_request would reject any other
                    target = self.select(h.model_id,
                                         adapter_id=req.adapter_id)
                except (ValueError, NoHealthyEngineError):
                    target = None
            if target is None:
                self._retire_unavailable(h, req)
                continue
            self._requeued.add(req.req_id)
            try:
                target.engine.adopt_request(req)
            except Exception:
                # the one chosen target refused (bounded queue, shape cap
                # mismatch between heterogeneous replicas): placement is
                # impossible NOW — retire deterministically rather than
                # shopping the request around the fleet
                self._retire_unavailable(h, req)
                continue
            moved_counter.inc()
            # literal event names at BOTH sites (not one parameterized
            # emit): the TPL010 docs-parity collector only sees literals
            if moved_counter is self._m_migrated:
                self._trace.emit("req.migrate", req.req_id,
                                 label=target.engine_id)
            else:
                self._trace.emit("req.requeue", req.req_id,
                                 label=target.engine_id)

    def _retire_unavailable(self, h: EngineHandle, req: Request) -> None:
        """Deterministic dead end: retire ``req`` with
        ``finish_reason="unavailable"`` (journaled tokens, if any,
        deliver — they were already streamed) and drop its move-once
        mark NOW: the id will never be seen again, so keeping the mark
        would leak it forever (the ``_requeued`` growth bug)."""
        self._m_unplaceable.inc()
        self._requeued.discard(req.req_id)
        try:
            h.engine.retire_queued(req, "unavailable")
        except Exception:
            # even the source engine's emit path is dead: the router
            # still owes the caller an output exactly once — synthesize
            # it into the stash run() merges from — AND the terminal
            # stream chunk a streaming client is blocked on (via the
            # engine's _safe_cb so the 3-arg/4-arg protocol and
            # isolation stay in one place; pure host code, guarded)
            self._stash[req.req_id] = RequestOutput(
                req_id=req.req_id, prompt_token_ids=req.prompt,
                token_ids=list(req.resume_tokens or ()),
                finish_reason="unavailable")
            if req.stream_cb is not None:
                try:
                    h.engine._safe_cb(req, None, "unavailable",
                                      len(req.resume_tokens or ()))
                except Exception:
                    pass

    # ---------------------------------------------------------------- drive
    @property
    def has_work(self) -> bool:
        return any(self._safe_has_work(h)
                   for h in list(self._handles.values()))

    def _safe_has_work(self, h: EngineHandle) -> bool:
        """``engine.has_work`` with crash containment: a probe that
        raises gates the engine down (its readable requests evacuate via
        :meth:`_contain`, after which it genuinely has no work here)."""
        if h.state == DOWN:
            return False
        try:
            return bool(h.engine.has_work)
        except Exception as e:
            self._contain(h, e)
            return False

    def step(self) -> None:
        """One fleet sweep: refresh health gates (auto-draining anything
        that tripped), then step every non-down engine that has work.

        CRASH CONTAINMENT: an exception escaping one engine's ``step()``
        — or its ``has_work``/``health()`` probes (hardware fault, bug,
        armed ``router.engine_step`` injection) — never propagates: that
        engine is marked ``down``
        (``paddle_tpu_router_engine_crash_total``), its waiting requests
        requeue and its in-flight requests migrate by token journal,
        and the sweep continues with the next engine. A single engine
        death is invisible to every other tenant of the fleet."""
        if self._retry_budget is not None:
            self._retry_budget.refill()  # one sweep's worth of tokens
        self._refresh_health()
        for h in list(self._handles.values()):
            if h.state == DOWN:
                continue
            try:
                if not h.engine.has_work:
                    continue
                faults.point("router.engine_step")
                h.engine.step()
            except Exception as e:
                self._contain(h, e)
        # reap move-once marks of moved requests that retired on their
        # adoptive engine: a step()-driven server (never calling run())
        # must not grow _requeued forever across incidents. Free in the
        # steady state (the set is empty unless a failover happened);
        # after one, a single guarded pass keeps only ids still live
        # somewhere in the fleet.
        if self._requeued:
            live = self._live_req_ids()
            if live is not None:
                self._requeued &= live
        if self._wal is not None:
            self._wal_commit_and_flush()

    def _live_req_ids(self) -> Optional[set]:
        """Every req_id currently queued or in-flight on any non-down
        engine; None when some engine's state is unreadable (reaping
        aborts for that sweep rather than dropping a mark that might
        still be live). The slot scan covers EVERY in-flight request —
        decoding slots and all concurrently chunk-prefilling slots alike
        (the unified-step engine parks a request in its slot at
        admission, so there is no out-of-slot "active prefill" state to
        enumerate separately; the old single-`_active_prefill` probe
        would silently drop every concurrent chunked prefill but one
        from migration accounting)."""
        live: set = set()
        try:
            for h in self._handles.values():
                if h.state == DOWN:
                    continue  # evacuated: holds no router-managed work
                eng = h.engine
                for req in eng.scheduler.waiting:
                    live.add(req.req_id)
                for st in eng.slots:
                    if st is not None:
                        live.add(st.req.req_id)
        except Exception:
            return None
        return live

    def _contain(self, h: EngineHandle, exc: BaseException) -> None:
        """Contain one engine's failure: count it, record it on the
        handle (surfaces via ``/healthz?engine=``), gate it ``down``,
        and evacuate everything it held."""
        self._m_crash.labels(engine_id=h.engine_id,
                             model_id=h.model_id).inc()
        h.last_error = repr(exc)
        with self._lock:
            h.state = DOWN
        self._set_state_gauge(h)
        self._evacuate(h)
        try:
            # post-mortem first responder: the last window_s seconds of
            # fleet timeline — the victim's per-request histories with
            # the export/adopt hop just taken — land on disk before
            # anyone asks. A failed dump (armed tracing.dump fault,
            # full disk) loses diagnostics, never containment.
            self._trace.dump_flight(reason="crash")
        except Exception:
            pass

    def _evacuate(self, h: EngineHandle) -> None:
        """Empty a just-downed engine: in-flight requests migrate FIRST
        (their tokens are sunk cost and their streams have live
        consumers — under tight sibling capacity they must not lose
        their seat to a request that never started), then waiting
        requests requeue — each exactly once. Nothing raises even if the
        engine is too dead to cooperate (every engine touch inside is
        guarded)."""
        self._migrate_inflight(h)
        self._requeue_waiting(h)

    def take_outputs(self) -> Dict[object, RequestOutput]:
        """Outputs finished fleet-wide since the last collection, merged
        across engines plus anything the router synthesized
        (``_retire_unavailable`` dead ends) — exactly-once handout. The
        incremental collector a PACED driver (``paddle_tpu.loadgen``)
        needs: call it after each :meth:`step` instead of waiting for
        :meth:`run` to drain the whole fleet."""
        out = self._stash
        self._stash = {}
        for h in list(self._handles.values()):
            try:
                out.update(h.engine.take_outputs())
            except Exception:
                # a dead engine's outputs were already evacuated/stashed
                # by containment; never let its corpse break collection
                pass
        return out

    def run(self) -> Dict[object, RequestOutput]:
        """Drive :meth:`step` until the whole fleet drains; returns every
        output finished since the last :meth:`run`, merged across engines
        (a requeued or migrated request's output comes from its adoptive
        engine) — exactly-once handout, same contract as
        ``ServingEngine.run``."""
        while self.has_work:
            self.step()
        out = self.take_outputs()
        # the fleet is fully drained: every request has retired, so NO
        # live request can still hold a move-once mark. Clearing (rather
        # than subtracting the delivered ids) also reaps marks of
        # requests that retired without router-visible output —
        # cancelled on the adoptive engine, drained via engine.run() —
        # which used to leak forever (tests assert the set is empty
        # after every chaos drill)
        self._requeued.clear()
        return out

    def stash_unclaimed(self, outputs: Dict[object, RequestOutput]) -> None:
        """Hand back outputs a caller collected but does not own (a front
        door draining the fleet for its own req_ids); they merge into the
        next :meth:`run`'s return."""
        self._stash.update(outputs)

    # ---------------------------------------------------------- durability
    def _durable_cb(self, wal_id: int) -> Callable:
        """The stream wrapper every WAL-armed request decodes under:
        chunks land in the router's buffer instead of the client — the
        group commit at the end of :meth:`step` journals them and THEN
        releases them (commit-then-emit). The wrapper itself never
        raises, so the engine's callback isolation never fires for a
        durable stream; client exceptions surface at flush time and
        cost only the attachment, never the request."""
        def cb(rid, tok, fin, seq):
            self._chunk_buf.append((wal_id, rid, tok, fin, seq))
        return cb

    def _inflight_fsm_states(self) -> Dict[object, Optional[int]]:
        """Fleet-wide ``{req_id: grammar FSM state}`` snapshot for the
        group commit (guarded per engine: a dead engine's slots were
        already evacuated, and a raising probe must not block the
        commit of every other request's tokens)."""
        out: Dict[object, Optional[int]] = {}
        for h in list(self._handles.values()):
            if h.state == DOWN:
                continue
            try:
                out.update(h.engine.inflight_fsm_states())
            except Exception:
                pass
        return out

    def _wal_commit_and_flush(self) -> None:
        """The group commit closing one :meth:`step`: fold this step's
        buffered chunks into one ``progress`` record per request (plus
        ``retire`` for terminals), pay ONE fsync for the whole batch —
        admits framed by :meth:`submit` since the last barrier ride the
        same commit — and only then release the chunks to client
        callbacks. A crash before the fsync loses tokens no client ever
        saw (deterministic decode regenerates them identically); a crash
        after it loses only deliveries the client can replay via
        :meth:`attach_stream` — exactly-once across process death."""
        buf, self._chunk_buf = self._chunk_buf, []
        if buf:
            fsm = self._inflight_fsm_states()
            per: Dict[int, dict] = {}
            order: List[int] = []
            for wid, rid, tok, fin, _seq in buf:
                rec = per.get(wid)
                if rec is None:
                    per[wid] = rec = {"tokens": [], "fin": None,
                                      "rid": rid}
                    order.append(wid)
                if tok is not None:
                    rec["tokens"].append(int(tok))
                if fin:
                    rec["fin"] = str(fin)
            for wid in order:
                rec = per[wid]
                at = self._wal_cursor.get(wid, 0)
                if rec["tokens"]:
                    # the end-of-step FSM snapshot corresponds exactly
                    # to the journal INCLUDING this delta, which is the
                    # cursor position replay validates it against
                    self._wal.append("progress", id=wid, at=at,
                                     tokens=rec["tokens"],
                                     fsm=fsm.get(rec["rid"]))
                    self._wal_cursor[wid] = at + len(rec["tokens"])
                if rec["fin"] is not None:
                    self._wal.append("retire", id=wid,
                                     reason=rec["fin"])
        self._wal.commit()
        for wid, rid, tok, fin, seq in buf:
            self._deliver(wid, rid, tok, fin, seq)
        for wid, rid, _tok, fin, _seq in buf:
            if fin:
                # terminal delivered: release the durable-stream state
                # (the WAL keeps the durable copy; compaction reaps it)
                self._client_cbs.pop(wid, None)
                self._stream_hist.pop(wid, None)
                self._wal_cursor.pop(wid, None)
                self._wal_ids.pop(rid, None)

    def _deliver(self, wid: int, rid, tok, fin, seq) -> None:
        """Release one committed chunk: record it in the in-memory
        stream history (what :meth:`attach_stream` replays) and forward
        to the attached client, if any. Durable-stream callback
        isolation: a raising client loses its ATTACHMENT — the chunk is
        already journaled, so a reattach replays it — never the
        request (contrast the WAL-off engine path, where a broken
        callback retires the request ``"error"``: with no journal there
        is nothing to reattach to)."""
        self._stream_hist.setdefault(wid, []).append((seq, tok, fin))
        cb = self._client_cbs.get(wid)
        if cb is None:
            return
        try:
            cb(rid, tok, fin, seq)
        except Exception:
            self._client_cbs.pop(wid, None)

    def attach_stream(self, wal_id: int, stream_cb: Callable,
                      after_seq: int = -1) -> int:
        """(Re)attach a client callback to a durable stream by WAL id —
        the client half of exactly-once across process death: pass the
        last seq you saw as ``after_seq`` and every chunk after it
        replays from the journal history, then live chunks follow.
        Recovery aliases resolve (a request re-admitted by
        :meth:`recover` answers to its pre-crash id), and the resolved
        id is returned. Commit-then-emit makes the cursor sound: the
        client can never have seen a chunk the journal does not hold,
        so the replay + live handoff has no gap to fall into."""
        wid = int(wal_id)
        seen: set = set()
        while wid in self._wal_alias and wid not in seen:
            seen.add(wid)
            wid = self._wal_alias[wid]
        rid = next((r for r, w in self._wal_ids.items() if w == wid),
                   None)
        hist = list(self._stream_hist.get(wid, ()))
        for seq, tok, fin in hist:
            if seq > after_seq:
                try:
                    stream_cb(rid, tok, fin, seq)
                except Exception:
                    return wid          # client broke mid-replay
        if not (hist and hist[-1][2]):  # stream still live: go live
            self._client_cbs[wid] = stream_cb
        return wid

    def recover(self, wal_dir: Optional[str] = None,
                ckpt_dir: Optional[str] = None,
                grammar_resolver: Optional[Callable] = None
                ) -> Dict[int, dict]:
        """Replay the WAL and re-admit every unfinished request onto
        whatever engines THIS router has — the process-restart half of
        the durability contract. Call after ``add_model`` (the restarted
        fleet may have fewer or more replicas than the one that died;
        placement is ordinary least-loaded dispatch). ``wal_dir`` arms
        the WAL if the router was built without one; ``ckpt_dir`` first
        rolls the newest committed checkpoint into the fleet
        (:meth:`reload`) so recovered streams decode under the exact
        weights a deploy intended. ``grammar_resolver(key) -> GrammarFSM``
        rebuilds constrained requests' DFAs from their journaled spec
        key ``(pattern, vocab_size, eos_token_id)``; the default lowers
        through :func:`~.grammar.toy_tokenizer` (every test/bench
        tokenizer in-repo) — front doors with a real tokenizer supply
        their own.

        Replay is pure (replay twice ⇒ the same state) and re-admission
        is idempotent: each re-admitted incarnation journals a
        ``recover`` record superseding the old id, so a second
        :meth:`recover` — same process or the next one — finds nothing
        pending it doesn't already own. Per request the outcome is
        ``resumed`` (re-admitted through the journaled re-prefill path:
        prompt + committed tokens re-prefill, decode continues
        token-identically, emission resumes at the journaled seq),
        ``completed`` (journal already terminal — only the retire
        record was torn off the tail), ``expired`` (its deadline lapsed
        across the death, measured on the WALL clock from the original
        admission), or ``failed`` (no engine could adopt it) —
        ``paddle_tpu_wal_recovered_requests_total{outcome}`` counts
        each. Returns ``{old_wal_id: outcome dict}``."""
        if self._wal is None:
            if wal_dir is None:
                raise ValueError(
                    "no WAL armed: construct Router(wal_dir=...) or "
                    "pass recover(wal_dir=...)")
            self._wal = RequestWAL(wal_dir)
        if ckpt_dir is not None:
            self.reload(ckpt_dir)
        state = self._wal.replay()
        # rebuild the alias chain from PRIOR incarnations' recover
        # records, so a client holding a two-crashes-ago id still
        # resolves to the live stream
        for wr in state.requests.values():
            if wr.superseded_by is not None:
                self._wal_alias[wr.wal_id] = wr.superseded_by
        live_now = set(self._wal_ids.values())
        results: Dict[int, dict] = {}
        for wr in state.pending():
            if wr.wal_id in live_now:
                continue    # admitted by THIS process: nothing to do
            results[wr.wal_id] = self._recover_one(wr, grammar_resolver)
        self._wal.commit()
        return results

    def _recover_one(self, wr: WalRequest,
                     grammar_resolver: Optional[Callable]) -> dict:
        """Re-admit ONE journaled request (see :meth:`recover`)."""
        toks = list(wr.tokens)
        done = None
        if wr.max_new_tokens and len(toks) >= wr.max_new_tokens:
            done = "length"
        elif (wr.eos_token_id is not None and toks
              and toks[-1] == int(wr.eos_token_id)):
            done = "stop"
        if done is not None:
            # the journal is already terminal — the crash tore away only
            # the retire record; close it out, no engine needed
            self._wal.append("retire", id=wr.wal_id, reason=done)
            self._stream_hist[wr.wal_id] = (
                [(i, t, None) for i, t in enumerate(toks)]
                + [(len(toks), None, done)])
            self._m_recovered.labels(outcome="completed").inc()
            return {"outcome": "completed", "finish_reason": done,
                    "tokens": toks, "wal_id": wr.wal_id, "rid": None}
        remaining = None
        if wr.deadline_s is not None:
            remaining = wr.deadline_s - max(
                0.0, time.time() - wr.admit_walltime)
            if remaining <= 0:
                self._wal.append("retire", id=wr.wal_id,
                                 reason="expired")
                self._stream_hist[wr.wal_id] = (
                    [(i, t, None) for i, t in enumerate(toks)]
                    + [(len(toks), None, "expired")])
                self._m_recovered.labels(outcome="expired").inc()
                return {"outcome": "expired", "tokens": toks,
                        "wal_id": wr.wal_id, "rid": None}
        try:
            grammar = None
            if wr.grammar_key is not None:
                if grammar_resolver is not None:
                    grammar = grammar_resolver(wr.grammar_key)
                else:
                    pattern, vocab, eos = wr.grammar_key
                    grammar = GrammarFSM.compile(
                        pattern, toy_tokenizer(vocab, eos))
            wid = self._wal.new_id()
            req = Request(
                prompt=np.asarray(wr.prompt, np.int32),
                max_new_tokens=wr.max_new_tokens,
                temperature=wr.temperature,
                eos_token_id=wr.eos_token_id, seed=wr.seed,
                stream_cb=self._durable_cb(wid),
                deadline_s=remaining, prefix_cache=wr.prefix_cache,
                priority=wr.priority, resume_tokens=toks,
                adapter_id=wr.adapter_id, grammar=grammar,
                resume_fsm_state=wr.fsm_state)
            target = self.select(wr.model, adapter_id=wr.adapter_id)
            target.engine.adopt_request(req)
        except Exception as e:
            # nothing on the restarted fleet can take it (model not
            # registered, adapter not loaded, grammar unbuildable, every
            # engine gated out): retire it deterministically in the LOG
            # — the caller sees "failed" + the tokens, never a silent
            # forever-pending record
            self._wal.append("retire", id=wr.wal_id,
                             reason="unavailable")
            self._m_recovered.labels(outcome="failed").inc()
            return {"outcome": "failed", "error": repr(e),
                    "tokens": toks, "wal_id": wr.wal_id, "rid": None}
        # adopted: supersede the old incarnation and journal the new one
        # WITH its carried journal — the next crash recovers from the
        # new record alone (original deadline fields ride along so
        # elapsed time is never double-counted across restarts)
        self._wal.append("recover", old=wr.wal_id, new=wid)
        payload = req.wal_admission(wid, model=wr.model,
                                    walltime=wr.admit_walltime,
                                    resume_from=wr.wal_id)
        payload["deadline_s"] = wr.deadline_s
        self._wal.append("admit", **payload)
        self._wal_ids[req.req_id] = wid
        self._wal_cursor[wid] = len(toks)
        self._wal_alias[wr.wal_id] = wid
        self._stream_hist[wid] = [(i, t, None)
                                  for i, t in enumerate(toks)]
        cb = self._client_cbs.pop(wr.wal_id, None)
        if cb is not None:
            self._client_cbs[wid] = cb
        self._count_dispatch(target)
        self._trace.emit("req.recover", req.req_id,
                         arg=float(len(toks)), label=target.engine_id)
        self._m_recovered.labels(outcome="resumed").inc()
        return {"outcome": "resumed", "rid": req.req_id, "wal_id": wid,
                "tokens": toks}

    def shutdown(self, drain: bool = True) -> Dict[object, RequestOutput]:
        """Graceful shutdown: drain the fleet, group-commit the last
        window, and SEAL the WAL (a ``seal`` record marks clean exit —
        the next process's :meth:`recover` finds nothing pending and no
        torn tail). ``drain=False`` skips the run-to-empty (commits and
        closes WITHOUT sealing, so pending work correctly reads as
        recoverable). Returns the final outputs; pair with
        :meth:`install_signal_handlers` for the SIGTERM →
        drain → seal → exit-0 path."""
        if drain:
            out = self.run()
        else:
            out = self.take_outputs()
        if self._wal is not None:
            self._wal_commit_and_flush()
            if not self.has_work:
                self._wal.seal()
            self._wal.close()
            self._wal = None
        return out

    def install_signal_handlers(self, signals=(_signal.SIGTERM,),
                                exit_on_shutdown: bool = True):
        """Arm SIGTERM (by default) to run :meth:`shutdown` — the
        serving twin of ``checkpoint.save_on_signal``, riding the SAME
        shared scope (:func:`paddle_tpu.faults.install_signal_handler`):
        training checkpoints-and-exits, serving drains-seals-and-exits,
        one signal path. Returns the scope (``uninstall()`` restores the
        previous handlers; also a context manager)."""
        def _handler(signum, frame):
            try:
                self.shutdown()
            finally:
                scope.uninstall()
            if exit_on_shutdown:
                import sys
                sys.exit(0)
        scope = faults.install_signal_handler(_handler, signals=signals)
        return scope

    # ------------------------------------------------------- manual gating
    def drain(self, engine_id: str) -> None:
        """Gate an engine out of admission (state ``draining``): waiting
        requests move to healthy siblings (exactly once), in-flight work
        keeps stepping to completion. ``undrain`` returns it."""
        h = self._require(engine_id)
        with self._lock:
            h.state = DRAINING
        self._set_state_gauge(h)
        self._requeue_waiting(h)

    def mark_down(self, engine_id: str) -> None:
        """Take an engine out NOW (state ``down``): waiting requests are
        requeued and in-flight requests MIGRATE by token journal (each
        exactly once — the adoptive engine continues every stream
        token-identically; unplaceable work retires ``"unavailable"``
        with its tokens so far), and the engine is no longer stepped
        until :meth:`undrain`. Never raises: every engine touch is
        guarded, so an engine that is already dead — its ``cancel``/
        ``step`` raising, its pool unusable — is still markable down."""
        h = self._require(engine_id)
        with self._lock:
            h.state = DOWN
        self._set_state_gauge(h)
        self._evacuate(h)

    def undrain(self, engine_id: str) -> None:
        """Return a drained/down engine to rotation (state ``healthy``;
        the next health refresh re-derives ``degraded`` if its watchdog
        is still tripped)."""
        h = self._require(engine_id)
        with self._lock:
            h.state = HEALTHY
        self._set_state_gauge(h)

    def _require(self, engine_id: str) -> EngineHandle:
        h = self._handles.get(str(engine_id))
        if h is None:
            raise KeyError(
                f"unknown engine id {engine_id!r} (known: "
                f"{sorted(self._handles)})")
        return h

    # -------------------------------------------------------------- reload
    def reload(self, checkpoint_dir: str, model: Optional[str] = None,
               step: Optional[int] = None,
               warm_prompt: Sequence[int] = (1,)) -> Dict[str, object]:
        """Rolling weight push for ONE model's engines (``model`` may be
        omitted only when the router serves a single model — a checkpoint
        belongs to one architecture, and pushing it fleet-wide by default
        would drain and corrupt unrelated tenants): engine by engine —
        gate it ``draining`` (no new admissions), finish its in-flight
        and queued work while the rest of the fleet keeps serving,
        restore the newest committed checkpoint (checksum-verified;
        ``step=`` pins one), and re-warm with a canary request before
        returning it to rotation.

        The restore is IN-PLACE (``set_state_dict``), so the compiled
        decode step sees the new weights as data: no recompile, and
        ``paddle_tpu_jit_compiles_total{fn="serving_step"}`` stays at
        one compile per bucket per engine across the push. A canary that retires
        ``nan``/``error`` marks that engine ``down`` (bad checkpoint never
        re-enters rotation) and the push continues; the summary reports
        per-engine results. Accepts a ``capture_train_state``-shaped state
        (uses its ``"model"`` subtree) or a bare ``state_dict``."""
        from ..checkpoint import CheckpointManager

        mgr = CheckpointManager(checkpoint_dir, max_to_keep=None)
        state, ck_step = mgr.restore(step=step)
        sd = state["model"] if isinstance(state, dict) and "model" in state \
            else state
        # host-side copy of every leaf: set_state_dict would otherwise
        # alias ONE device array into every replica's params, and the
        # compiled step DONATES its state buffers — the first engine's
        # post-reload step would invalidate the weights under every
        # sibling ("buffer has been deleted or donated"). From numpy,
        # each set_state_dict materializes a private device buffer.
        sd = {k: np.asarray(v.numpy() if hasattr(v, "numpy") else v)
              for k, v in sd.items()}
        # resolve like every other routing entry point: None means "the
        # single served model" and is an actionable error otherwise
        mid = self._resolve_model(model)
        results: List[Dict[str, object]] = []
        for h in self._models[mid]:
            if h.state == DOWN:
                results.append({"engine_id": h.engine_id,
                                "result": "skipped-down"})
                continue
            results.append(self._reload_one(h, sd, ck_step, warm_prompt))
        return {"step": ck_step, "engines": results}

    def _reload_one(self, h: EngineHandle, sd, ck_step: int,
                    warm_prompt: Sequence[int]) -> Dict[str, object]:
        with self._lock:
            h.state = DRAINING
        self._set_state_gauge(h)
        # drain: the WHOLE fleet keeps stepping (live traffic continues on
        # siblings; draining gates h out of NEW admissions) until h
        # finishes its in-flight AND already-queued work locally. Queued
        # work deliberately does NOT requeue here: a rolling push visits
        # every sibling next, so moving requests ahead of the wave would
        # double-move them — and the exactly-once failover budget belongs
        # to real failures, not planned maintenance.
        # bound the drain on the gate state too: if the engine crashes
        # mid-drain AND is too dead to evacuate (its queue/slots stay
        # populated), step() skips it as DOWN forever — without this
        # condition the loop would spin on has_work for eternity. The
        # probe itself rides _safe_has_work: a raising has_work gates
        # the engine down (contained) instead of escaping reload()
        # with the engine stuck DRAINING
        while h.state != DOWN and self._safe_has_work(h):
            self.step()
        if h.state == DOWN:
            # the engine crashed while draining (step() containment
            # already moved its work): don't push weights into a corpse,
            # and don't resurrect it to healthy below
            self._m_reloads.labels(result="error").inc()
            return {"engine_id": h.engine_id, "result": "error",
                    "error": h.last_error}
        try:
            missing, _unexpected = h.engine.model.set_state_dict(sd)
            if missing:
                raise ValueError(
                    f"checkpoint is missing {len(missing)} model keys "
                    f"(first: {missing[:3]}); refusing a partial weight "
                    f"load on engine {h.engine_id}")
            if h.engine.prefix_cache is not None:
                # the radix cache holds KV computed under the OLD
                # weights: a warm hit after the push would mix stale
                # prefix KV with new-weight suffix compute — flush it
                # (pages return to the pool; the cache re-warms from
                # post-reload traffic)
                h.engine.prefix_cache.clear()
            canary_ok, reason = self._warm(h, warm_prompt)
        except Exception:
            # restore itself failed (shape mismatch, corrupt leaf): the
            # engine's weights are suspect — gate it down, surface the
            # error; siblings keep serving the old version
            with self._lock:
                h.state = DOWN
            self._set_state_gauge(h)
            self._m_reloads.labels(result="error").inc()
            raise
        if not canary_ok:
            with self._lock:
                h.state = DOWN
            self._set_state_gauge(h)
            self._m_reloads.labels(result="error").inc()
            return {"engine_id": h.engine_id, "result": "error",
                    "canary_finish_reason": reason}
        h.weights_step = ck_step
        with self._lock:
            h.state = HEALTHY
        self._set_state_gauge(h)
        self._m_reloads.labels(result="ok").inc()
        return {"engine_id": h.engine_id, "result": "ok",
                "weights_step": ck_step}

    def _warm(self, h: EngineHandle, warm_prompt: Sequence[int],
              **canary_kwargs):
        """Canary decode on the freshly loaded weights: one tiny request
        end-to-end (prefill + one decode token) re-warms the compiled
        programs and proves the checkpoint produces finite logits before
        the engine rejoins rotation. Extra kwargs ride the canary
        request — ``register_adapter`` warms THROUGH the new adapter
        (``adapter_id=``), proving its weights finite under live
        compute. Returns (ok, finish_reason)."""
        eng = h.engine
        wid = eng.add_request(np.asarray(warm_prompt, np.int32),
                              max_new_tokens=1, **canary_kwargs)
        while eng.has_work:
            eng.step()
        outs = eng.take_outputs()
        warm = outs.pop(wid)
        if outs:  # real outputs scooped alongside the canary: hand back
            self._stash.update(outs)
        return warm.finish_reason in ("stop", "length"), warm.finish_reason

    # ------------------------------------------------------------- adapters
    def register_adapter(self, name: str, weights,
                         model: Optional[str] = None,
                         warm_prompt: Sequence[int] = (1,)
                         ) -> Dict[str, object]:
        """Hot-load LoRA adapter ``name`` onto EVERY non-down engine of
        ``model``, under live traffic: per engine, install the weights
        (a pure value write into the stacked adapter arrays — the
        compiled step is untouched, so zero recompiles and zero dropped
        in-flight work; no drain, unlike :meth:`reload`) and prove them
        with a one-token canary routed THROUGH the adapter. A canary
        that retires abnormally rolls that engine's install back
        (unregister) and reports ``"error"`` — a bad adapter never
        enters rotation, and siblings that passed keep serving it.
        Returns a per-engine summary; after an all-ok push,
        ``select(adapter_id=name)`` sees the whole fleet."""
        mid = self._resolve_model(model)
        results: List[Dict[str, object]] = []
        for h in self._models[mid]:
            if h.state == DOWN:
                results.append({"engine_id": h.engine_id,
                                "result": "skipped-down"})
                continue
            try:
                h.engine.register_adapter(name, weights)
                canary_ok, reason = self._warm(h, warm_prompt,
                                               adapter_id=name)
            except Exception as e:
                self._m_adapter_loads.labels(result="error").inc()
                results.append({"engine_id": h.engine_id,
                                "result": "error", "error": repr(e)})
                continue
            if not canary_ok:
                # roll back: the adapter produced non-finite logits (or
                # the canary died) — this engine must not advertise it
                try:
                    h.engine.unregister_adapter(name)
                except Exception:
                    pass
                self._m_adapter_loads.labels(result="error").inc()
                results.append({"engine_id": h.engine_id,
                                "result": "error",
                                "canary_finish_reason": reason})
                continue
            self._m_adapter_loads.labels(result="ok").inc()
            results.append({"engine_id": h.engine_id, "result": "ok"})
        return {"adapter": name, "engines": results}

    def unregister_adapter(self, name: str,
                           model: Optional[str] = None) -> None:
        """Remove adapter ``name`` from every non-down engine of
        ``model``. Raises (before touching ANY engine) if a live request
        still uses it anywhere — drain the tenant first."""
        mid = self._resolve_model(model)
        ups = [h for h in self._models[mid] if h.state != DOWN]
        for h in ups:
            if h.engine.adapters.holds(name) \
                    and h.engine._adapter_in_use(name):
                raise ValueError(
                    f"adapter {name!r} is in use on engine "
                    f"{h.engine_id}; drain it before unregistering")
        for h in ups:
            if h.engine.adapters.holds(name):
                h.engine.unregister_adapter(name)

    # -------------------------------------------------------------- health
    @staticmethod
    def _engine_health_view(h: EngineHandle) -> Dict[str, object]:
        """``engine.health()`` guarded for the scrape thread: a raising
        probe reads as a non-ok status instead of 500-ing ``/healthz``.
        Containment (gate down + evacuate) stays the DRIVE thread's job
        — ``_refresh_health`` does it at the next ``router.step()``."""
        try:
            return dict(h.engine.health())
        except Exception as e:
            return {"status": f"probe-error: {e!r}"}

    def health(self, engine: Optional[str] = None) -> Dict[str, object]:
        """Aggregate (or per-engine, via ``engine=``) health view for
        ``MetricsServer(health_cb=router.health)``.

        Aggregate ``status`` is ``"ok"`` unless some served model has NO
        engine that is both router-healthy and watchdog-ok — one degraded
        replica keeps /healthz 200 (its siblings cover), a fully dark
        model flips 503. ``/healthz?engine=<id>`` routes here with
        ``engine=`` set; an unknown id reports non-ok and names the known
        ids."""
        # snapshot the topology under the lock: the scrape thread must
        # not iterate dicts the driver thread's add_model() is growing
        with self._lock:
            handles = list(self._handles.values())
            model_map = {mid: list(hs) for mid, hs in self._models.items()}
        if engine is not None:
            h = next((x for x in handles if x.engine_id == str(engine)),
                     None)
            if h is None:
                return {"status": "unknown-engine",
                        "engine": str(engine),
                        "known": sorted(x.engine_id for x in handles)}
            eh = self._engine_health_view(h)
            ok = h.state == HEALTHY and eh["status"] == "ok"
            return {"status": "ok" if ok else
                    (h.state if h.state != HEALTHY else "degraded"),
                    "state": h.state, "model": h.model_id,
                    "weights_step": h.weights_step,
                    "last_error": h.last_error, **{
                        k: v for k, v in eh.items() if k != "status"}}
        models: Dict[str, Dict[str, int]] = {}
        all_ok = True
        for mid, hs in model_map.items():
            healthy = sum(
                1 for h in hs if h.state == HEALTHY
                and self._engine_health_view(h)["status"] == "ok")
            models[mid] = {"healthy": healthy, "total": len(hs)}
            if healthy == 0:
                all_ok = False
        if self._last_health_ok and not all_ok:
            # the /healthz 200→503 transition (some model just went
            # fully dark): auto-dump the recorder exactly once per
            # transition, from whichever thread (driver or scrape)
            # observed it first
            try:
                self._trace.dump_flight(reason="healthz")
            except Exception:
                pass
        self._last_health_ok = all_ok
        return {"status": "ok" if all_ok else "degraded",
                "models": models,
                "engines": {h.engine_id: h.state for h in handles}}
