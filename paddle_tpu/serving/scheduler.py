"""Continuous-batching scheduler: FCFS admission under a token budget.

The policy half of the serving engine (the mechanism — pages, compiled
steps — lives in engine.py/kv_cache.py). Requests queue FCFS; each engine
step admits waiting requests into free batch slots as long as

1. a fixed decode slot is free (the compiled step's batch is padded to
   ``max_batch_slots``, so slots — not requests — bound concurrency),
2. the KV pool can cover the request's WORST CASE (prompt + max_new
   tokens) on top of every live reservation (kv_cache.can_admit) — with
   no preemption, admitting on hope would strand a sequence mid-decode,
3. this step's prefill token budget is not exhausted — prefill compute is
   O(prompt²) while decode is O(1) per live sequence, so unbounded
   admission would stall every running stream for one giant prompt
   (the continuous-batching latency win this budget protects).

Head-of-line semantics: strict FCFS — if the head request doesn't fit,
nothing behind it is admitted (no starvation of big prompts).
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from .. import metrics

__all__ = ["Request", "RequestOutput", "FCFSScheduler"]

_req_counter = itertools.count()


@dataclass
class Request:
    """One generation request (the engine's admission unit)."""

    prompt: np.ndarray  # [S] int32 token ids
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    # called with (req_id, token_id, finished) as each token lands —
    # the streaming front door (serving/api.py) hangs SSE-ish chunks off
    # it. finished is False per token; the terminal call passes token=None
    # and the finish-reason string ("stop"|"length") as finished (truthy)
    stream_cb: Optional[Callable] = None
    req_id: object = field(default_factory=lambda: next(_req_counter))
    # enqueue wall-clock (perf_counter domain): queue-wait and TTFT are
    # measured from here, so they include scheduling delay, not just
    # model time — the serving-SLO definition
    arrival_t: float = field(default_factory=time.perf_counter)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    @property
    def max_total_tokens(self) -> int:
        return int(self.prompt.size) + int(self.max_new_tokens)


@dataclass
class RequestOutput:
    """Terminal state of a request (engine.step() returns these)."""

    req_id: object
    prompt_token_ids: np.ndarray
    token_ids: List[int]            # generated tokens (incl. eos if hit)
    finish_reason: str              # "stop" (eos) | "length"
    n_gen: int = 0

    def __post_init__(self):
        self.n_gen = len(self.token_ids)


class FCFSScheduler:
    """FCFS waiting queue + per-step admission (policy only: slot/page
    bookkeeping stays in the engine/pool)."""

    def __init__(self, max_batch_slots: int,
                 prefill_token_budget: int = 1024):
        if max_batch_slots < 1:
            raise ValueError("max_batch_slots must be >= 1")
        self.max_batch_slots = int(max_batch_slots)
        self.prefill_token_budget = int(prefill_token_budget)
        self.waiting: deque = deque()
        self._m_queue_wait = metrics.get_registry().histogram(
            "paddle_tpu_serving_queue_wait_seconds",
            "Time a request waits in the FCFS queue before admission")

    def add(self, request: Request) -> None:
        self.waiting.append(request)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def admit(self, free_slots: int, pool) -> List[Request]:
        """Pop the FCFS prefix that fits this step: free decode slots,
        worst-case page reservations, and the prefill token budget."""
        admitted: List[Request] = []
        budget = self.prefill_token_budget
        # pages promised to THIS step's earlier admissions: the pool only
        # records a reservation at prefill (after admit returns), so
        # can_admit must be charged for batch-mates or two big requests
        # admitted together could jointly over-commit the pool
        pending_pages = 0
        while self.waiting and free_slots > 0:
            req = self.waiting[0]
            if req.prompt.size > budget and admitted:
                break  # budget spent this step; FCFS head keeps its turn
            # (an over-budget prompt with no batch-mates still runs, alone
            # this step, or it would starve forever)
            if not pool.can_admit(req.max_total_tokens, pending_pages):
                break  # head-of-line blocks: no overtaking, no starvation
            self.waiting.popleft()
            admitted.append(req)
            self._m_queue_wait.observe(time.perf_counter() - req.arrival_t)
            pending_pages += pool.pages_needed(req.max_total_tokens)
            free_slots -= 1
            budget -= int(req.prompt.size)
            if budget <= 0:
                break
        return admitted
