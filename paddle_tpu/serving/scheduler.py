"""Chunked-prefill scheduler: priority admission + a shared token budget.

The policy half of the serving engine (the mechanism — pages, compiled
steps — lives in engine.py/kv_cache.py). Two decisions per engine step:

**Admission** (:meth:`FCFSScheduler.admit`): waiting requests enter free
batch slots in (priority tier, arrival) order as long as

1. a fixed decode slot is free (the compiled step's batch is padded to
   ``max_batch_slots``, so slots — not requests — bound concurrency),
2. the KV pool can cover the request's WORST CASE (prompt + max_new
   tokens) on top of every live reservation (kv_cache.can_admit) — with
   no preemption, admitting on hope would strand a sequence mid-decode.

Admission no longer gates on prompt length: a 10k-token prompt admits
immediately and *prefills in chunks* across subsequent steps, so one
giant prompt never has to wait for (or monopolize) a step.

**Chunking** (:meth:`FCFSScheduler.plan_chunks`): every step has a fixed
``token_budget`` shared by the whole batch. Decode tokens are charged
FIRST — decode-first under load: a running stream's next token is never
displaced by prompt work — and mid-prefill slots split the remainder in
SLO order (priority tier, then earliest deadline, then arrival), each
taking as much of its remaining prompt as the budget leaves. Prefill
compute is O(prompt x cache) while decode is O(cache) per sequence, so
the budget is what bounds a step's cost — and with it the inter-token
latency every decoding tenant observes (docs/SERVING.md "Unified step &
chunked prefill").

Head-of-line semantics: strict within the priority order — if the head
request doesn't fit the pool, nothing behind it is admitted (no
starvation of big prompts by small ones of the same tier; a HIGHER tier
request enqueues ahead and is not blocked by a lower tier's head).
"""
from __future__ import annotations

import itertools
import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults, metrics

__all__ = ["BackpressureError", "Request", "RequestOutput", "FCFSScheduler"]


class BackpressureError(RuntimeError):
    """The scheduler queue is full: the request was REJECTED, not queued.

    Carries ``retry_after_s`` — the engine's drain-rate estimate of when
    a slot is likely to open — so an HTTP front door can map this
    straight onto ``429 Too Many Requests`` + ``Retry-After``. Rejecting
    at enqueue bounds memory AND tail latency: a request that would wait
    forever is better told so immediately (docs/RESILIENCE.md).
    """

    def __init__(self, message: str, retry_after_s: float,
                 queue_depth: int):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.queue_depth = int(queue_depth)

_req_counter = itertools.count()


@dataclass
class Request:
    """One generation request (the engine's admission unit)."""

    prompt: np.ndarray  # [S] int32 token ids
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    # called with (req_id, token_id, finished) as each token lands —
    # the streaming front door (serving/api.py) hangs SSE-ish chunks off
    # it. finished is False per token; the terminal call passes token=None
    # and the finish-reason string ("stop"|"length") as finished (truthy)
    stream_cb: Optional[Callable] = None
    # seconds from enqueue until the engine retires the request with
    # finish_reason="timeout" (queued or mid-decode); None = no deadline
    deadline_s: Optional[float] = None
    # False opts this request out of prefix-cache matching AND insertion
    # (docs/SERVING.md "Prefix caching"): it prefills from token 0 and
    # shares no pages — the per-request escape hatch under the
    # engine-level ServingEngine(prefix_cache=) flag
    prefix_cache: bool = True
    # SLO tier: lower is more urgent (0 = default). Honored at ADMISSION
    # (the queue orders by (priority, arrival) — a tier-0 request
    # enqueues ahead of every waiting tier-1 request) and at CHUNKING
    # (higher tiers' prompt chunks take the step's token budget first),
    # docs/SERVING.md "Unified step & chunked prefill". Within a tier,
    # deadline-bearing requests chunk earliest-deadline-first.
    priority: int = 0
    # resume journal (docs/RESILIENCE.md "In-flight migration"): tokens
    # this request already generated on an engine that died. Set by
    # ServingEngine.export_inflight; an adopting engine re-prefills
    # prompt + resume_tokens and continues decoding at the journaled
    # position — per-request deterministic sampling makes the continued
    # stream token-identical to an uninterrupted run. The tokens were
    # already streamed (stream_cb seq 0..len-1); emission resumes at
    # seq=len(resume_tokens), so a client never sees a duplicate.
    resume_tokens: Optional[List[int]] = None
    # multi-LoRA tenancy (docs/SERVING.md "Multi-LoRA adapters"): the
    # NAME of the adapter this request decodes under, or None for the
    # base model (slot 0, the zero-delta identity). Names — not slots —
    # travel with the request: each engine resolves the name against
    # ITS AdapterStore at admission, so a migrated request lands on
    # whatever slot the adoptive engine holds the same weights in
    adapter_id: Optional[str] = None
    # constrained decoding (docs/SERVING.md "Constrained decoding"): a
    # compiled serving.grammar.GrammarFSM, or None for free text. The
    # engine interns its mask table at admission and masks this
    # request's sample rows inside the compiled step
    grammar: Optional[object] = None
    # FSM journal, the grammar sibling of resume_tokens
    # (docs/RESILIENCE.md "In-flight migration"): the LOCAL DFA state
    # after the journaled tokens, set by ServingEngine.export_inflight.
    # Engine-independent (local, not table-offset), so an adoptive
    # engine resumes mid-structure without replaying the walk — and a
    # None journal is recomputed from resume_tokens, which must agree
    resume_fsm_state: Optional[int] = None
    req_id: object = field(default_factory=lambda: next(_req_counter))
    # enqueue wall-clock (perf_counter domain): queue-wait and TTFT are
    # measured from here, so they include scheduling delay, not just
    # model time — the serving-SLO definition
    arrival_t: float = field(default_factory=time.perf_counter)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        # canonicalize the seed into int32 range (keep the low 32 bits):
        # the compiled decode step stages per-slot seeds as an int32
        # array, and numpy raises OverflowError staging e.g. 2**31 — a
        # user-supplied seed must never be able to crash a decode step.
        # Deterministic (same wide seed -> same stream) and applied
        # before ANY key derivation, so host prefill and device decode
        # agree on the exact same value.
        s = int(self.seed) & 0xFFFFFFFF
        self.seed = s - (1 << 32) if s >= (1 << 31) else s
        self.priority = int(self.priority)
        if self.adapter_id is not None and not isinstance(self.adapter_id,
                                                          str):
            raise ValueError("adapter_id must be a registered adapter "
                             "NAME (str) or None for the base model")
        if self.grammar is not None and not hasattr(self.grammar,
                                                    "mask_table"):
            raise ValueError(
                "grammar must be a compiled serving.grammar.GrammarFSM "
                "(use GrammarFSM.compile(pattern, tokenizer))")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise ValueError("deadline_s must be >= 0")
        # the deadline clock starts at ENQUEUE (same SLO domain as TTFT):
        # queue wait burns budget, so an overloaded engine times requests
        # out instead of serving them arbitrarily late
        self.deadline = (faults.Deadline(self.deadline_s)
                         if self.deadline_s is not None else None)

    @property
    def max_total_tokens(self) -> int:
        return int(self.prompt.size) + int(self.max_new_tokens)

    @property
    def prefill_tokens(self) -> int:
        """Tokens the admitting engine will actually prefill: the prompt,
        plus the journaled generation for a migrated request (its ragged
        re-prefill covers prompt + tokens-so-far) — what the scheduler's
        per-step prefill budget must charge."""
        return int(self.prompt.size) + len(self.resume_tokens or ())

    def admission_ids(self) -> np.ndarray:
        """The token ids an admitting engine will prefill: prompt, plus
        the journal for a migrated request — what the prefix cache is
        matched against (engine and scheduler probe the SAME ids, so the
        budget charge and the actual match cannot drift)."""
        if not self.resume_tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.resume_tokens, np.int32)])

    @property
    def remaining_new_tokens(self) -> int:
        """Decode tokens still owed (max_new_tokens minus any journaled
        resume tokens) — the honest load-score weight for a migrated
        request."""
        return max(int(self.max_new_tokens)
                   - len(self.resume_tokens or ()), 0)

    def wal_admission(self, wal_id: int, model: Optional[str] = None,
                      walltime: Optional[float] = None,
                      resume_from: Optional[int] = None) -> dict:
        """The JSON-able WAL admission record (serving/wal.py) for this
        request: every field a RESTARTED process needs to rebuild it
        exactly — sampling identity (prompt, canonical seed, temperature,
        eos), SLO identity (priority, original deadline_s + the wall
        clock it started burning at), tenancy (adapter name, grammar
        spec KEY — pattern/vocab/eos, rebuildable, never the compiled
        tables), the prefix_cache opt-out, and the journal carried so
        far (resume tokens + FSM state) when this admission IS a
        recovery re-admission (``resume_from`` names the incarnation it
        supersedes). Living next to the field list keeps the durable
        record and the dataclass from drifting."""
        return {
            "id": int(wal_id), "model": model,
            "prompt": [int(t) for t in self.prompt],
            "max_new_tokens": int(self.max_new_tokens),
            "temperature": float(self.temperature),
            "eos": (None if self.eos_token_id is None
                    else int(self.eos_token_id)),
            "seed": int(self.seed), "priority": int(self.priority),
            "deadline_s": (None if self.deadline_s is None
                           else float(self.deadline_s)),
            "t": time.time() if walltime is None else float(walltime),
            "adapter_id": self.adapter_id,
            "grammar": (list(self.grammar.key)
                        if self.grammar is not None else None),
            "prefix_cache": bool(self.prefix_cache),
            "resume_from": resume_from,
            "tokens": [int(t) for t in (self.resume_tokens or ())],
            "fsm": (None if self.resume_fsm_state is None
                    else int(self.resume_fsm_state)),
        }


@dataclass
class RequestOutput:
    """Terminal state of a request (engine.step() returns these)."""

    req_id: object
    prompt_token_ids: np.ndarray
    token_ids: List[int]            # generated tokens (incl. eos if hit)
    # "stop" (eos) | "length" | "timeout" | "cancelled" | "nan"
    # (quarantined) | "error" | "unavailable" (router requeue impossible)
    # | "expired" (deadline lapsed while still queued — pages never
    # allocated) — docs/SERVING.md has the full table
    finish_reason: str
    n_gen: int = 0
    error: Optional[str] = None     # diagnostic for finish_reason="error"

    def __post_init__(self):
        self.n_gen = len(self.token_ids)


class FCFSScheduler:
    """Priority-tiered waiting queue + per-step admission + chunk
    planning (policy only: slot/page bookkeeping stays in the
    engine/pool). The name survives from the PR 1 pure-FCFS scheduler;
    within one priority tier the order is still first-come-first-served,
    and the default tier makes the whole queue plain FCFS."""

    def __init__(self, max_batch_slots: int,
                 token_budget: int = 1024,
                 max_queue: Optional[int] = None,
                 retry_after_cb: Optional[Callable[[], float]] = None):
        if max_batch_slots < 1:
            raise ValueError("max_batch_slots must be >= 1")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None: unbounded)")
        if token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        self.max_batch_slots = int(max_batch_slots)
        # the per-STEP token budget shared by decode (charged first) and
        # prompt chunks (docs/SERVING.md "Unified step & chunked
        # prefill") — the lever trading a long prompt's TTFT against
        # every decoding tenant's inter-token latency
        self.token_budget = int(token_budget)
        # backpressure bound: add() rejects with BackpressureError past
        # this depth. retry_after_cb computes the hint from live drain
        # rate (the engine installs its step-time EWMA); the fallback
        # heuristic assumes ~10 admissions/s per slot.
        self.max_queue = None if max_queue is None else int(max_queue)
        self._retry_after_cb = retry_after_cb
        self.waiting: deque = deque()
        # deadline-bearing requests currently queued: keeps the per-step
        # expiry sweep free (early return) for the common all-None case
        self._n_deadlined = 0
        # outstanding work queued here, in engine STEPS (1 prefill +
        # max_new_tokens decode steps per request) — maintained
        # incrementally at every queue mutation so the router's
        # least-loaded scoring (engine.load_score) stays O(1) per probe
        # instead of rescanning the deque on the dispatch hot path
        self._pending_steps = 0
        reg = metrics.get_registry()
        self._m_queue_wait = reg.histogram(
            "paddle_tpu_serving_queue_wait_seconds",
            "Time a request waits in the FCFS queue before admission")
        self._m_rejections = reg.counter(
            "paddle_tpu_serving_queue_rejections_total",
            "Requests rejected at enqueue because the bounded queue was "
            "full (BackpressureError)")

    @property
    def prefill_token_budget(self) -> int:
        """Deprecated alias of :attr:`token_budget` (the PR 1 name): the
        budget now bounds the WHOLE step's tokens — decode first, prompt
        chunks in the remainder — not a separate prefill phase."""
        return self.token_budget

    def _retry_after(self) -> float:
        if self._retry_after_cb is not None:
            return max(float(self._retry_after_cb()), 0.0)
        return max(0.05, 0.1 * len(self.waiting) / self.max_batch_slots)

    def _step_charge(self, request: Request) -> int:
        """Engine steps this request will consume end-to-end: its prompt
        chunks under the step token budget (a 10k prompt at budget 256
        is ~40 steps of prefill, and the router's least-loaded scoring
        must see them) plus one decode step per remaining new token."""
        chunks = -(-request.prefill_tokens // self.token_budget)
        return max(chunks, 1) + request.remaining_new_tokens

    def add(self, request: Request) -> None:
        """Queue a request in (priority, arrival) order — FCFS within a
        tier — or raise :class:`BackpressureError` when the bounded
        queue is full (never silently drops, never grows unboundedly;
        priority does not bypass backpressure)."""
        if self.max_queue is not None and len(self.waiting) >= self.max_queue:
            self._m_rejections.inc()
            hint = self._retry_after()
            raise BackpressureError(
                f"scheduler queue full ({len(self.waiting)}/{self.max_queue}"
                f" waiting, limit: max_queue={self.max_queue}); retry in "
                f"~{hint:.3f}s", retry_after_s=hint,
                queue_depth=len(self.waiting))
        # stable tier insert: after every waiting request of <= priority
        # (arrival order within a tier), before the first lower tier
        idx = len(self.waiting)
        while idx > 0 and self.waiting[idx - 1].priority > request.priority:
            idx -= 1
        if idx == len(self.waiting):
            self.waiting.append(request)
        else:
            self.waiting.insert(idx, request)
        self._pending_steps += self._step_charge(request)
        if request.deadline is not None:
            self._n_deadlined += 1

    def pop_expired(self) -> List[Request]:
        """Pull every deadline-expired request out of the queue in ONE
        pass (a mass-expiry sweep must stay O(n), not O(k*n) — a large
        idle backlog could otherwise trip the step watchdog on its own
        bookkeeping). Free when nothing queued carries a deadline."""
        if self._n_deadlined == 0:
            return []
        expired: List[Request] = []
        alive: deque = deque()
        for r in self.waiting:
            if r.deadline is not None and r.deadline.expired():
                expired.append(r)
            else:
                alive.append(r)
        self.waiting = alive
        self._n_deadlined -= len(expired)
        for r in expired:
            self._pending_steps -= self._step_charge(r)
        return expired

    def pop_all(self) -> List[Request]:
        """Empty the waiting queue in FCFS order and return the requests —
        the router's drain path (requeue onto a healthy engine). O(1)
        bookkeeping: the deque is handed over wholesale."""
        out = list(self.waiting)
        self.waiting = deque()
        self._n_deadlined = 0
        self._pending_steps = 0
        return out

    def remove(self, req_id) -> Optional[Request]:
        """Pull a WAITING request out of the queue (cancellation path);
        None if it is not queued (already admitted or unknown)."""
        # by index, not deque.remove: dataclass equality would compare
        # prompt arrays elementwise (and raise on mixed lengths)
        for i, r in enumerate(self.waiting):
            if r.req_id == req_id:
                del self.waiting[i]
                self._pending_steps -= self._step_charge(r)
                if r.deadline is not None:
                    self._n_deadlined -= 1
                return r
        return None

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def pending_steps(self) -> int:
        """Estimated engine steps queued here (prefill + decode tokens);
        the queue half of ``ServingEngine.load_score``."""
        return self._pending_steps

    def admit(self, free_slots: int, pool,
              max_priority: Optional[int] = None) -> List[Request]:
        """Pop the (priority, arrival)-ordered prefix that fits this
        step: free decode slots and worst-case page reservations.

        Prompt LENGTH no longer gates admission — an admitted request's
        prefill runs in chunks under :meth:`plan_chunks`'s per-step
        budget, so a 10k-token prompt admits the moment a slot and its
        worst-case pages are available, and its TTFT clock starts
        making progress immediately instead of waiting for an idle
        step.

        ``max_priority`` is the brownout ladder's admission hold: a head
        whose priority EXCEEDS it stays queued (and, because the queue
        is priority-sorted, so does everything behind it — no lower tier
        can overtake a held one). The held work is not retired: it
        admits when the ladder steps back down, or falls to the deadline
        sweep."""
        admitted: List[Request] = []
        # pages promised to THIS step's earlier admissions: the pool only
        # records a reservation when the engine parks the request (after
        # admit returns), so can_admit must be charged for batch-mates or
        # two big requests admitted together could jointly over-commit
        # the pool. pending_cached tracks cache pages those admissions
        # will PIN — they must stop counting as reclaimable for later
        # batch-mates.
        pending_pages = 0
        pending_cached = 0
        while self.waiting and free_slots > 0:
            req = self.waiting[0]
            if max_priority is not None and req.priority > max_priority:
                break  # brownout hold: tiers above the cap stay queued
            # matched prefix pages join the block table by refcount, not
            # by a free-list draw (the probe walks the same radix index
            # the admission will match), so the page charge discounts
            # them — warm prompts admit alongside work a cold charge
            # would have deferred
            matched = (pool.prefix_match_len(req.admission_ids())
                       if req.prefix_cache else 0)
            cached_pages = matched // pool.page_size
            if not pool.can_admit(req.max_total_tokens, pending_pages,
                                  cached_pages=cached_pages,
                                  pending_cached=pending_cached):
                break  # head-of-line blocks: no overtaking, no starvation
            self.waiting.popleft()
            self._pending_steps -= self._step_charge(req)
            if req.deadline is not None:
                self._n_deadlined -= 1
            admitted.append(req)
            if req.resume_tokens is None:
                # queue-wait measures FIRST admission from the original
                # enqueue; a migrated request's second admission would
                # fold all its time on the dead engine into the
                # histogram, spiking p95 during exactly the incidents
                # operators read it for (same skew guard as TTFT).
                # `is None`, not falsy: a request migrated BETWEEN its
                # prompt chunks journals an EMPTY list — it was admitted
                # once already and must not re-observe either
                self._m_queue_wait.observe(
                    time.perf_counter() - req.arrival_t)
            pending_pages += (pool.pages_needed(req.max_total_tokens)
                              - cached_pages)
            pending_cached += cached_pages
            free_slots -= 1
        return admitted

    @staticmethod
    def offload_victims(head: Request,
                        candidates: Sequence[Tuple[float, object, Request]]
                        ) -> List[object]:
        """Pick which live slots may be parked to the host KV tier so the
        blocked queue head can admit (docs/SERVING.md "KV page tiers").
        ``candidates`` is ``[(last_active_t, key, request)]`` for slots
        eligible to park; returns their keys in park order. Two rules:
        only STRICTLY lower-priority tenants are preempted (a tie never
        thrashes two equal streams swapping each other out), and among
        those the coldest stream — oldest ``last_active_t`` — parks
        first, so the pages least likely to be needed next step leave
        HBM first."""
        eligible = [c for c in candidates if c[2].priority > head.priority]
        eligible.sort(key=lambda c: c[0])
        return [c[1] for c in eligible]

    def plan_chunks(self, n_decode: int,
                    prefills: Sequence[Tuple[object, int, Request]],
                    batch_cap: Optional[int] = None,
                    batch_priority: int = 2
                    ) -> List[Tuple[object, int]]:
        """Slice this step's prompt-chunk work under the shared token
        budget. ``n_decode`` decode tokens are charged FIRST —
        decode-first under load: a running stream's next token is never
        displaced by prompt work — and mid-prefill slots split the
        remainder in SLO order: priority tier, then earliest deadline
        (an SLO-bearing request inside a tier prefills ahead of
        unbounded ones), then arrival. ``prefills`` is
        ``[(key, remaining_prompt_tokens, request)]``; returns
        ``[(key, chunk_tokens)]`` in service order, chunks >= 1, for as
        many slots as the budget covers this step. Slots left out simply
        wait — decode retirements free budget within a bounded number of
        steps, so a prefill can lag but never starves forever.

        ``batch_cap`` (the brownout ``chunks-capped`` action) caps the
        PER-STEP chunk of any request at priority >= ``batch_priority``
        — batch-tier prefills trickle slower so the freed budget serves
        interactive chunks, but still progress >= 1 token/step (capped,
        never starved). Chunk sizes are planning data, so any cap value
        leaves the compile surface untouched."""
        left = max(self.token_budget - int(n_decode), 0)
        plan: List[Tuple[object, int]] = []
        if left <= 0 or not prefills:
            return plan
        order = sorted(
            prefills,
            key=lambda e: (e[2].priority,
                           e[2].deadline.remaining()
                           if e[2].deadline is not None else math.inf,
                           e[2].arrival_t))
        for key, remaining, req in order:
            if left <= 0:
                break
            chunk = min(int(remaining), left)
            if batch_cap is not None and req.priority >= batch_priority:
                chunk = min(chunk, int(batch_cap))
            if chunk <= 0:
                continue
            plan.append((key, chunk))
            left -= chunk
        return plan

    def plan_drafts(self, leftover: int,
                    wants: Sequence[Tuple[object, int, Request]]
                    ) -> List[Tuple[object, int]]:
        """Allocate speculative draft rows from the budget this step
        would otherwise leave idle. ``leftover`` is what remains AFTER
        decode tokens and prompt chunks are charged — speculation is
        strictly opportunistic: it never displaces a decoding tenant's
        next token (decode-first) nor a prompt chunk (prefill progress
        bounds TTFT; a rejected draft row is worthless next to it). The
        leftover splits in the same SLO order as :meth:`plan_chunks`
        (priority tier, earliest deadline, arrival), so when drafts must
        be rationed the latency-bounded streams speculate first.
        ``wants`` is ``[(key, max_draft_tokens, request)]``; returns
        ``[(key, granted)]`` with granted >= 1."""
        left = max(int(leftover), 0)
        plan: List[Tuple[object, int]] = []
        if left <= 0 or not wants:
            return plan
        order = sorted(
            wants,
            key=lambda e: (e[2].priority,
                           e[2].deadline.remaining()
                           if e[2].deadline is not None else math.inf,
                           e[2].arrival_t))
        for key, want, _req in order:
            if left <= 0:
                break
            d = min(int(want), left)
            if d <= 0:
                continue
            plan.append((key, d))
            left -= d
        return plan
