"""Continuous-batching scheduler: FCFS admission under a token budget.

The policy half of the serving engine (the mechanism — pages, compiled
steps — lives in engine.py/kv_cache.py). Requests queue FCFS; each engine
step admits waiting requests into free batch slots as long as

1. a fixed decode slot is free (the compiled step's batch is padded to
   ``max_batch_slots``, so slots — not requests — bound concurrency),
2. the KV pool can cover the request's WORST CASE (prompt + max_new
   tokens) on top of every live reservation (kv_cache.can_admit) — with
   no preemption, admitting on hope would strand a sequence mid-decode,
3. this step's prefill token budget is not exhausted — prefill compute is
   O(prompt²) while decode is O(1) per live sequence, so unbounded
   admission would stall every running stream for one giant prompt
   (the continuous-batching latency win this budget protects).

Head-of-line semantics: strict FCFS — if the head request doesn't fit,
nothing behind it is admitted (no starvation of big prompts).
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from .. import faults, metrics

__all__ = ["BackpressureError", "Request", "RequestOutput", "FCFSScheduler"]


class BackpressureError(RuntimeError):
    """The scheduler queue is full: the request was REJECTED, not queued.

    Carries ``retry_after_s`` — the engine's drain-rate estimate of when
    a slot is likely to open — so an HTTP front door can map this
    straight onto ``429 Too Many Requests`` + ``Retry-After``. Rejecting
    at enqueue bounds memory AND tail latency: a request that would wait
    forever is better told so immediately (docs/RESILIENCE.md).
    """

    def __init__(self, message: str, retry_after_s: float,
                 queue_depth: int):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.queue_depth = int(queue_depth)

_req_counter = itertools.count()


@dataclass
class Request:
    """One generation request (the engine's admission unit)."""

    prompt: np.ndarray  # [S] int32 token ids
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    # called with (req_id, token_id, finished) as each token lands —
    # the streaming front door (serving/api.py) hangs SSE-ish chunks off
    # it. finished is False per token; the terminal call passes token=None
    # and the finish-reason string ("stop"|"length") as finished (truthy)
    stream_cb: Optional[Callable] = None
    # seconds from enqueue until the engine retires the request with
    # finish_reason="timeout" (queued or mid-decode); None = no deadline
    deadline_s: Optional[float] = None
    # False opts this request out of prefix-cache matching AND insertion
    # (docs/SERVING.md "Prefix caching"): it prefills from token 0 and
    # shares no pages — the per-request escape hatch under the
    # engine-level ServingEngine(prefix_cache=) flag
    prefix_cache: bool = True
    # resume journal (docs/RESILIENCE.md "In-flight migration"): tokens
    # this request already generated on an engine that died. Set by
    # ServingEngine.export_inflight; an adopting engine re-prefills
    # prompt + resume_tokens and continues decoding at the journaled
    # position — per-request deterministic sampling makes the continued
    # stream token-identical to an uninterrupted run. The tokens were
    # already streamed (stream_cb seq 0..len-1); emission resumes at
    # seq=len(resume_tokens), so a client never sees a duplicate.
    resume_tokens: Optional[List[int]] = None
    req_id: object = field(default_factory=lambda: next(_req_counter))
    # enqueue wall-clock (perf_counter domain): queue-wait and TTFT are
    # measured from here, so they include scheduling delay, not just
    # model time — the serving-SLO definition
    arrival_t: float = field(default_factory=time.perf_counter)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        # canonicalize the seed into int32 range (keep the low 32 bits):
        # the compiled decode step stages per-slot seeds as an int32
        # array, and numpy raises OverflowError staging e.g. 2**31 — a
        # user-supplied seed must never be able to crash a decode step.
        # Deterministic (same wide seed -> same stream) and applied
        # before ANY key derivation, so host prefill and device decode
        # agree on the exact same value.
        s = int(self.seed) & 0xFFFFFFFF
        self.seed = s - (1 << 32) if s >= (1 << 31) else s
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise ValueError("deadline_s must be >= 0")
        # the deadline clock starts at ENQUEUE (same SLO domain as TTFT):
        # queue wait burns budget, so an overloaded engine times requests
        # out instead of serving them arbitrarily late
        self.deadline = (faults.Deadline(self.deadline_s)
                         if self.deadline_s is not None else None)

    @property
    def max_total_tokens(self) -> int:
        return int(self.prompt.size) + int(self.max_new_tokens)

    @property
    def prefill_tokens(self) -> int:
        """Tokens the admitting engine will actually prefill: the prompt,
        plus the journaled generation for a migrated request (its ragged
        re-prefill covers prompt + tokens-so-far) — what the scheduler's
        per-step prefill budget must charge."""
        return int(self.prompt.size) + len(self.resume_tokens or ())

    def admission_ids(self) -> np.ndarray:
        """The token ids an admitting engine will prefill: prompt, plus
        the journal for a migrated request — what the prefix cache is
        matched against (engine and scheduler probe the SAME ids, so the
        budget charge and the actual match cannot drift)."""
        if not self.resume_tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.resume_tokens, np.int32)])

    @property
    def remaining_new_tokens(self) -> int:
        """Decode tokens still owed (max_new_tokens minus any journaled
        resume tokens) — the honest load-score weight for a migrated
        request."""
        return max(int(self.max_new_tokens)
                   - len(self.resume_tokens or ()), 0)


@dataclass
class RequestOutput:
    """Terminal state of a request (engine.step() returns these)."""

    req_id: object
    prompt_token_ids: np.ndarray
    token_ids: List[int]            # generated tokens (incl. eos if hit)
    # "stop" (eos) | "length" | "timeout" | "cancelled" | "nan"
    # (quarantined) | "error" | "unavailable" (router requeue impossible)
    # — docs/SERVING.md has the full table
    finish_reason: str
    n_gen: int = 0
    error: Optional[str] = None     # diagnostic for finish_reason="error"

    def __post_init__(self):
        self.n_gen = len(self.token_ids)


class FCFSScheduler:
    """FCFS waiting queue + per-step admission (policy only: slot/page
    bookkeeping stays in the engine/pool)."""

    def __init__(self, max_batch_slots: int,
                 prefill_token_budget: int = 1024,
                 max_queue: Optional[int] = None,
                 retry_after_cb: Optional[Callable[[], float]] = None):
        if max_batch_slots < 1:
            raise ValueError("max_batch_slots must be >= 1")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None: unbounded)")
        self.max_batch_slots = int(max_batch_slots)
        self.prefill_token_budget = int(prefill_token_budget)
        # backpressure bound: add() rejects with BackpressureError past
        # this depth. retry_after_cb computes the hint from live drain
        # rate (the engine installs its step-time EWMA); the fallback
        # heuristic assumes ~10 admissions/s per slot.
        self.max_queue = None if max_queue is None else int(max_queue)
        self._retry_after_cb = retry_after_cb
        self.waiting: deque = deque()
        # deadline-bearing requests currently queued: keeps the per-step
        # expiry sweep free (early return) for the common all-None case
        self._n_deadlined = 0
        # outstanding work queued here, in engine STEPS (1 prefill +
        # max_new_tokens decode steps per request) — maintained
        # incrementally at every queue mutation so the router's
        # least-loaded scoring (engine.load_score) stays O(1) per probe
        # instead of rescanning the deque on the dispatch hot path
        self._pending_steps = 0
        reg = metrics.get_registry()
        self._m_queue_wait = reg.histogram(
            "paddle_tpu_serving_queue_wait_seconds",
            "Time a request waits in the FCFS queue before admission")
        self._m_rejections = reg.counter(
            "paddle_tpu_serving_queue_rejections_total",
            "Requests rejected at enqueue because the bounded queue was "
            "full (BackpressureError)")

    def _retry_after(self) -> float:
        if self._retry_after_cb is not None:
            return max(float(self._retry_after_cb()), 0.0)
        return max(0.05, 0.1 * len(self.waiting) / self.max_batch_slots)

    def add(self, request: Request) -> None:
        """Queue a request FCFS, or raise :class:`BackpressureError` when
        the bounded queue is full (never silently drops, never grows
        unboundedly)."""
        if self.max_queue is not None and len(self.waiting) >= self.max_queue:
            self._m_rejections.inc()
            hint = self._retry_after()
            raise BackpressureError(
                f"scheduler queue full ({len(self.waiting)}/{self.max_queue}"
                f" waiting, limit: max_queue={self.max_queue}); retry in "
                f"~{hint:.3f}s", retry_after_s=hint,
                queue_depth=len(self.waiting))
        self.waiting.append(request)
        self._pending_steps += 1 + request.remaining_new_tokens
        if request.deadline is not None:
            self._n_deadlined += 1

    def pop_expired(self) -> List[Request]:
        """Pull every deadline-expired request out of the queue in ONE
        pass (a mass-expiry sweep must stay O(n), not O(k*n) — a large
        idle backlog could otherwise trip the step watchdog on its own
        bookkeeping). Free when nothing queued carries a deadline."""
        if self._n_deadlined == 0:
            return []
        expired: List[Request] = []
        alive: deque = deque()
        for r in self.waiting:
            if r.deadline is not None and r.deadline.expired():
                expired.append(r)
            else:
                alive.append(r)
        self.waiting = alive
        self._n_deadlined -= len(expired)
        for r in expired:
            self._pending_steps -= 1 + r.remaining_new_tokens
        return expired

    def pop_all(self) -> List[Request]:
        """Empty the waiting queue in FCFS order and return the requests —
        the router's drain path (requeue onto a healthy engine). O(1)
        bookkeeping: the deque is handed over wholesale."""
        out = list(self.waiting)
        self.waiting = deque()
        self._n_deadlined = 0
        self._pending_steps = 0
        return out

    def remove(self, req_id) -> Optional[Request]:
        """Pull a WAITING request out of the queue (cancellation path);
        None if it is not queued (already admitted or unknown)."""
        # by index, not deque.remove: dataclass equality would compare
        # prompt arrays elementwise (and raise on mixed lengths)
        for i, r in enumerate(self.waiting):
            if r.req_id == req_id:
                del self.waiting[i]
                self._pending_steps -= 1 + r.remaining_new_tokens
                if r.deadline is not None:
                    self._n_deadlined -= 1
                return r
        return None

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def pending_steps(self) -> int:
        """Estimated engine steps queued here (prefill + decode tokens);
        the queue half of ``ServingEngine.load_score``."""
        return self._pending_steps

    def admit(self, free_slots: int, pool) -> List[Request]:
        """Pop the FCFS prefix that fits this step: free decode slots,
        worst-case page reservations, and the prefill token budget."""
        admitted: List[Request] = []
        budget = self.prefill_token_budget
        # pages promised to THIS step's earlier admissions: the pool only
        # records a reservation at prefill (after admit returns), so
        # can_admit must be charged for batch-mates or two big requests
        # admitted together could jointly over-commit the pool.
        # pending_cached tracks cache pages those admissions will PIN —
        # they must stop counting as reclaimable for later batch-mates
        pending_pages = 0
        pending_cached = 0
        while self.waiting and free_slots > 0:
            req = self.waiting[0]
            # prefill-cost honesty: the budget exists to bound prefill
            # COMPUTE this step, so charge only what will actually run —
            # prompt + journal (a migrated request's ragged re-prefill)
            # MINUS the cached prefix the engine's radix cache already
            # covers (the probe walks the same index the prefill will
            # match, floor 1: the last token always prefills). Matched
            # pages likewise don't draw from the free list, so admission
            # discounts them from the page charge too.
            matched = (pool.prefix_match_len(req.admission_ids())
                       if req.prefix_cache else 0)
            cost = max(req.prefill_tokens - matched, 1)
            cached_pages = matched // pool.page_size
            if cost > budget and admitted:
                break  # budget spent this step; FCFS head keeps its turn
            # (an over-budget prompt with no batch-mates still runs, alone
            # this step, or it would starve forever)
            if not pool.can_admit(req.max_total_tokens, pending_pages,
                                  cached_pages=cached_pages,
                                  pending_cached=pending_cached):
                break  # head-of-line blocks: no overtaking, no starvation
            self.waiting.popleft()
            self._pending_steps -= 1 + req.remaining_new_tokens
            if req.deadline is not None:
                self._n_deadlined -= 1
            admitted.append(req)
            if not req.resume_tokens:
                # queue-wait measures FIRST admission from the original
                # enqueue; a migrated request's second admission would
                # fold all its decode time on the dead engine into the
                # histogram, spiking p95 during exactly the incidents
                # operators read it for (same skew guard as TTFT)
                self._m_queue_wait.observe(
                    time.perf_counter() - req.arrival_t)
            pending_pages += (pool.pages_needed(req.max_total_tokens)
                              - cached_pages)
            pending_cached += cached_pages
            free_slots -= 1
            budget -= cost
            if budget <= 0:
                break
        return admitted
