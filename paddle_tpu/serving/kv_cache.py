"""Paged KV-cache pool: fixed page pool + per-sequence block tables.

The serving engine's memory substrate (PAPERS.md: Ragged Paged Attention,
arxiv 2604.15464 — vLLM-style paging on TPU): instead of one dense
``[B, max_len, nkv, hd]`` cache per request, every layer owns a fixed pool
of ``[num_pages, page_size, n_kv_heads, head_dim]`` K and V blocks, and a
sequence is a *list of page ids* (its block table). Admission, retirement,
and fork never move KV bytes — only page ids change hands — so the decode
step's shapes stay fixed while the live batch churns.

Page 0 is the reserved NULL page: block tables are 0-padded and idle batch
slots carry all-zero tables, so their (masked) KV writes land harmlessly
there instead of corrupting a live sequence. The allocator hands out pages
1..num_pages-1.

Sharing is REFCOUNTED and copy-on-write: ``fork`` shares every page of
the source (full and partial tail alike) by bumping refcounts, and the
first divergent append into a shared page copies it lazily
(:meth:`extend`'s write guard) — the sibling's bytes are never mutated.
:class:`PrefixCache` builds on the same refcounts: a per-engine radix
index keyed on token ids maps cached prompt prefixes to page lists, so a
request sharing a system prompt adopts the cached pages at admission and
ragged-prefills only its uncovered suffix (docs/SERVING.md "Prefix
caching"). Cache-resident pages that no live sequence references are
RECLAIMABLE: they never cause an allocation failure — ``_take_page``
evicts LRU cache nodes under pool pressure — and they are excluded from
``used_pages`` (which counts pages live sequences pin).

Allocation is LAZY (a page is taken from the free list only when a token
actually lands in it) but admission is accounted against each sequence's
worst case via ``reserve`` — the scheduler admits a request only if the
pool can cover every live sequence's ``prompt + max_new_tokens`` tail, so
a mid-decode out-of-pages abort is impossible without preemption.

Sharding note (GSPMD, arxiv 2105.04663): the pool keeps the kv-head axis
third, matching the dense cache layout the mp mesh shards today — a later
multi-chip serving PR can shard ``n_kv_heads`` over 'mp' without touching
the allocator or block tables (page ids are replicated host metadata).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import faults, metrics
from ..tensor import Tensor

faults.declare_point(
    "serving.kv_alloc",
    "PagedKVCachePool._take_page, before a page leaves the free list — "
    "arm ResourceExhausted here to drill pool-exhaustion handling")

__all__ = ["PagedKVCachePool", "PrefixCache", "page_bytes",
           "pages_for_hbm_budget"]


def page_bytes(page_size: int, n_kv_heads: int, head_dim: int,
               num_layers: int, dtype_bytes: int = 4) -> int:
    """Bytes one page costs across ALL layers (K and V)."""
    return 2 * num_layers * page_size * n_kv_heads * head_dim * dtype_bytes


def pages_for_hbm_budget(hbm_bytes: int, page_size: int, n_kv_heads: int,
                         head_dim: int, num_layers: int,
                         dtype_bytes: int = 4) -> int:
    """Pool sizing math (docs/SERVING.md): pages = HBM budget / page bytes,
    minus nothing — the caller budgets weights/activations separately."""
    per = page_bytes(page_size, n_kv_heads, head_dim, num_layers, dtype_bytes)
    return max(int(hbm_bytes) // per, 0)


class PagedKVCachePool:
    """Fixed K/V page pool per layer + block-table allocator.

    Device state: ``k_pools``/``v_pools`` — one framework Tensor per layer,
    shape ``[num_pages, page_size, n_kv_heads, head_dim]``. The compiled
    decode step consumes and returns them functionally; the engine swaps
    the fresh arrays back in via :meth:`set_arrays`.

    Host state: free list, per-page refcounts (fork shares full pages
    copy-on-nothing — pages are append-only once full), per-sequence block
    tables and lengths, worst-case reservations, and the high-water mark
    (``peak_used``) the page-reuse tests assert on.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.float32,
                 engine_id: str = "", model_id: str = ""):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        # identity labels for the pool gauges: an engine passes its own
        # {engine_id, model_id} so N pools behind a Router stay N series
        # instead of last-writer-wins; a standalone pool reports under the
        # empty-string labels
        self._lbl = {"engine_id": str(engine_id), "model_id": str(model_id)}
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        shape = (self.num_pages, self.page_size, self.n_kv_heads,
                 self.head_dim)
        self.k_pools: List[Tensor] = [
            Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
            for _ in range(self.num_layers)]
        self.v_pools: List[Tensor] = [
            Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
            for _ in range(self.num_layers)]
        # page 0 reserved: free list covers 1..num_pages-1 (LIFO for reuse
        # locality — a just-freed page is the next handed out)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._ref = np.zeros(self.num_pages, np.int32)
        # pages freed by a NaN quarantine: zeroed lazily the moment they
        # are re-taken (free() with scrub=True) — masked attention gives
        # padding lanes weight 0, but 0 x NaN = NaN, so a poisoned page
        # must never enter a new block table un-scrubbed. Lazy keeps the
        # quarantine itself O(1): no full-pool rewrite per retirement.
        self._dirty: set = set()
        # refcount-aware deferred scrub (docs/RESILIENCE.md "Quarantine x
        # refcounts"): a quarantined victim's free(scrub=True) must NOT
        # zero a page a sibling fork / the prefix cache still reads —
        # such pages are only MARKED here, and the mark converts to a
        # real scrub when the LAST reference drops (whoever drops it),
        # so a suspect page can never re-enter circulation un-scrubbed.
        self._scrub_pending: set = set()
        # optional per-engine prefix cache; PrefixCache attaches itself
        self.prefix_cache: Optional["PrefixCache"] = None
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        self._resv: Dict[object, int] = {}
        self.peak_used = 0
        reg = metrics.get_registry()
        _eng = ("engine_id", "model_id")
        self._m_pages_used = reg.gauge(
            "paddle_tpu_serving_kv_pages_used",
            "KV pages currently allocated out of the pool",
            labels=_eng).labels(**self._lbl)
        self._m_pages_total = reg.gauge(
            "paddle_tpu_serving_kv_pages_total",
            "Usable KV pages in the pool (page 0 reserved excluded)",
            labels=_eng).labels(**self._lbl)
        self._m_page_events = reg.counter(
            "paddle_tpu_serving_kv_page_events_total",
            "Page allocator traffic", labels=("event",) + _eng)
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        """Re-set BOTH pool gauges on every allocator event: the total is
        re-published (not just set once at construction) so a registry
        ``reset()`` mid-life self-heals instead of reporting 0 capacity
        forever. Each pool owns its {engine_id, model_id} series; the
        family-level read aggregates the fleet (docs/OBSERVABILITY.md)."""
        self._m_pages_used.set(self.used_pages)
        self._m_pages_total.set(self.usable_pages)

    # ---------------------------------------------------------- accounting
    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def used_pages(self) -> int:
        """Pages pinned by LIVE sequences. Cache-resident pages no
        sequence references are excluded: they are reclaimable on demand
        (evict-then-retry in :meth:`_take_page`), so counting them as
        used would make a warm cache read as pressure it isn't."""
        return (self.usable_pages - len(self._free)
                - self._reclaimable_pages())

    def _reclaimable_pages(self) -> int:
        """Pages held ONLY by the prefix cache — evictable the moment an
        allocation needs them."""
        return (self.prefix_cache.reclaimable_pages()
                if self.prefix_cache is not None else 0)

    def utilization(self) -> float:
        return self.used_pages / max(self.usable_pages, 1)

    def pages_needed(self, n_tokens: int) -> int:
        return max(math.ceil(int(n_tokens) / self.page_size), 1)

    def _unallocated_reserved(self) -> int:
        """Pages promised to live sequences but not yet drawn from the
        free list (their lazy tails)."""
        return sum(max(r - len(self._tables[s]), 0)
                   for s, r in self._resv.items())

    def can_admit(self, max_total_tokens: int,
                  pending_pages: int = 0, cached_pages: int = 0,
                  pending_cached: int = 0) -> bool:
        """True when the pool can cover a new sequence's WORST CASE
        (``max_total_tokens`` = prompt + max_new_tokens) on top of every
        live sequence's outstanding reservation — the no-preemption
        admission guarantee. ``pending_pages`` charges pages promised to
        requests admitted earlier in the same scheduler step, whose
        reservations are not recorded here until their prefill runs.
        ``cached_pages`` discounts pages the prefix cache already holds
        for this request's prompt (they join its table by refcount, not
        by a free-list draw). Matched pages must ALSO leave the
        reclaimable side: the moment the request adopts them their
        refcount pins them, so counting them both as "not needed" and as
        "evictable for someone else" would double-count and overcommit —
        the victim being some LIVE sequence's reserved tail.
        ``pending_cached`` extends the same exclusion to pages matched
        by earlier same-step admissions (conservative when two
        batch-mates match the SAME pages: under-admission just waits a
        step; overcommit kills a tenant)."""
        need = self.pages_needed(max_total_tokens) - int(cached_pages)
        reclaim = max(self._reclaimable_pages() - int(cached_pages)
                      - int(pending_cached), 0)
        avail = len(self._free) + reclaim - self._unallocated_reserved()
        return need + int(pending_pages) <= avail

    # ---------------------------------------------------------- allocation
    def _take_page(self) -> int:
        faults.point("serving.kv_alloc")
        # cache-never-starves-tenants: under pool pressure, evict LRU
        # unreferenced prefix-cache nodes until a page frees — the cache
        # must never turn a coverable allocation into a failure
        while not self._free and self.prefix_cache is not None:
            if not self.prefix_cache.evict_one():
                break
        if not self._free:
            raise RuntimeError(
                "KV page pool exhausted — admission accounting should have "
                "prevented this (reserve() not called?)")
        p = self._free.pop()
        if p in self._dirty:
            # a quarantined page is about to re-enter a block table:
            # scrub ALL dirty pages in one batched update per layer
            # (each .at[].set copies the whole pool, so amortize the
            # copies over every pending page instead of paying them
            # per page)
            pages = jnp.asarray(sorted(self._dirty), jnp.int32)
            for li in range(self.num_layers):
                kp = self.k_pools[li]._value
                vp = self.v_pools[li]._value
                self.k_pools[li] = Tensor(
                    kp.at[pages].set(jnp.zeros((), kp.dtype)),
                    stop_gradient=True)
                self.v_pools[li] = Tensor(
                    vp.at[pages].set(jnp.zeros((), vp.dtype)),
                    stop_gradient=True)
            self._dirty.clear()
        self._ref[p] = 1
        self.peak_used = max(self.peak_used, self.used_pages)
        self._m_page_events.labels(event="alloc", **self._lbl).inc()
        self._refresh_gauges()
        return p

    def allocate(self, seq_id, n_tokens: int,
                 max_total_tokens: Optional[int] = None,
                 prefix_pages: Sequence[int] = (),
                 prefix_tokens: int = 0) -> List[int]:
        """Create a sequence holding ``n_tokens`` of KV (the prompt), with
        a worst-case reservation of ``max_total_tokens`` (defaults to
        ``n_tokens``). Returns the block table.

        ``prefix_pages``/``prefix_tokens`` seed the table with SHARED
        pages (a prefix-cache hit): each is adopted by refcount — no
        free-list draw, no KV copy — and the prefix refs are bumped
        BEFORE any fresh page is taken, so a mid-allocate eviction can
        never reclaim the very pages this sequence is adopting. Rollback
        (:meth:`free`) drops shared and fresh pages uniformly."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        if prefix_tokens and int(prefix_tokens) % self.page_size:
            raise ValueError(
                f"prefix_tokens {prefix_tokens} must be page-aligned "
                f"(page_size={self.page_size}) — prefix sharing is "
                f"full-page granular")
        resv = self.pages_needed(max_total_tokens
                                 if max_total_tokens is not None
                                 else n_tokens)
        table: List[int] = []
        for p in prefix_pages:
            self._ref[p] += 1
            table.append(p)
        self._tables[seq_id] = table
        self._lens[seq_id] = int(prefix_tokens)
        self._resv[seq_id] = resv
        if int(n_tokens) > int(prefix_tokens):
            try:
                self.extend(seq_id, n_tokens)
            except Exception:
                # atomic: a mid-allocate failure (real exhaustion or an
                # armed serving.kv_alloc fault) must not leak a half-built
                # sequence — roll back pages already taken and the
                # bookkeeping entries
                self.free(seq_id)
                raise
        # n_tokens == prefix_tokens is the chunked-prefill admission
        # path: the sequence starts as EXACTLY its adopted prefix (zero
        # fresh pages, zero writable-page checks — the next write lands
        # at position prefix_tokens, a page this table doesn't hold yet,
        # so CoW-copying the shared tail page here would only break the
        # sharing the adoption just paid for)
        self.peak_used = max(self.peak_used, self.used_pages)
        return list(self._tables[seq_id])

    def extend(self, seq_id, total_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``total_tokens`` of KV, and
        guarantee the LAST slot (the one about to be written) lives in a
        page this sequence owns exclusively — the copy-on-write seam: a
        fork/prefix-share diverging into a shared page copies it here,
        first, so the sibling's (and the cache's) bytes are immutable."""
        table = self._tables[seq_id]
        need = self.pages_needed(total_tokens)
        while len(table) < need:
            table.append(self._take_page())
        self._lens[seq_id] = max(self._lens[seq_id], int(total_tokens))
        self._ensure_writable(seq_id, int(total_tokens) - 1)

    def extend_write(self, seq_id, start: int, total_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``total_tokens`` of KV and
        make EVERY page holding positions ``start .. total_tokens-1``
        exclusively owned — the multi-token variant of :meth:`extend`'s
        one-slot CoW seam. A unified-step prompt chunk scatters a whole
        token range in one compiled program, so any page it touches that
        a fork sibling or the prefix cache still references must be
        copied first (freshly drawn pages are exclusive by construction;
        in practice only the range's FIRST page can be shared — a
        partially written fork tail)."""
        start, total = int(start), int(total_tokens)
        if total <= start:
            return
        table = self._tables[seq_id]
        need = self.pages_needed(total)
        while len(table) < need:
            table.append(self._take_page())
        self._lens[seq_id] = max(self._lens[seq_id], total)
        for pi in range(start // self.page_size,
                        (total - 1) // self.page_size + 1):
            self._ensure_page_writable(seq_id, pi)

    def truncate(self, seq_id, total_tokens: int) -> None:
        """Roll ``seq_id``'s KV length back to ``total_tokens`` — the
        speculative-decoding reject path: draft rows past the accepted
        prefix wrote KV for tokens that were never committed, and
        lowering ``_lens`` is ALL the rollback there is. The pages stay
        in the table (they sit inside the admission-time reservation, so
        nothing else can claim them) and their stale bytes are inert:
        paged attention masks every row at its own position, so KV past
        the sequence length is never gathered, and the next committed
        write at those positions scatters right over it. Refcounts are
        untouched — the rejected range was already made exclusively
        owned by the :meth:`extend_write` that reserved it, and a CoW'd
        page stays correctly owned for the retry."""
        total = int(total_tokens)
        cur = self._lens[seq_id]
        if total < 0 or total > cur:
            raise ValueError(
                f"truncate({seq_id!r}, {total}) outside [0, {cur}] — "
                f"rollback can only shorten a sequence")
        self._lens[seq_id] = total

    def _ensure_writable(self, seq_id, token_pos: int) -> None:
        """Copy-on-write: if the page holding ``token_pos`` is shared
        (refcount > 1 — a fork sibling or the prefix cache also holds
        it), copy its contents into a fresh page and swap the block-table
        entry, leaving the shared original untouched."""
        if token_pos < 0:
            return
        self._ensure_page_writable(seq_id, token_pos // self.page_size)

    def _ensure_page_writable(self, seq_id, pi: int) -> None:
        """CoW one block-table entry by page index (the shared seam of
        :meth:`extend` and :meth:`extend_write`)."""
        table = self._tables[seq_id]
        old = table[pi]
        if self._ref[old] <= 1:
            return
        fresh = self._take_page()
        for li in range(self.num_layers):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(kp.at[fresh].set(kp[old]),
                                      stop_gradient=True)
            self.v_pools[li] = Tensor(vp.at[fresh].set(vp[old]),
                                      stop_gradient=True)
        table[pi] = fresh
        # the shared original loses OUR reference only (cannot hit zero:
        # ref was > 1); scrub state, if any, stays with the original
        self._ref[old] -= 1
        self._m_page_events.labels(event="cow", **self._lbl).inc()
        self.peak_used = max(self.peak_used, self.used_pages)
        self._refresh_gauges()

    def append_token(self, seq_id) -> None:
        """Make room for one more token (the engine calls this right before
        the decode step writes position ``seq_len``)."""
        self.extend(seq_id, self._lens[seq_id] + 1)

    def _release_ref(self, p: int, scrub: bool = False) -> bool:
        """Drop ONE reference on page ``p`` (the single choreography every
        release path — sequence retirement, cache eviction — goes
        through, so scrub semantics cannot drift between them). Returns
        True when the page actually hit the free list.

        Refcount-aware scrub: a ``scrub=True`` release while siblings
        still hold the page must neither zero it now (a healthy tenant
        is reading those bytes) nor forget it — the page is marked
        scrub-pending, and WHOEVER drops the last reference (even a
        normal ``scrub=False`` retirement, even a cache eviction)
        converts the mark into a real lazy scrub before reuse."""
        self._ref[p] -= 1
        if self._ref[p] > 0:
            if scrub:
                self._scrub_pending.add(p)
            return False
        self._free.append(p)
        if scrub or p in self._scrub_pending:
            self._dirty.add(p)
        self._scrub_pending.discard(p)
        self._m_page_events.labels(event="free", **self._lbl).inc()
        return True

    def free(self, seq_id, scrub: bool = False) -> None:
        """Retire a sequence NOW: drop refcounts, return exclusive pages to
        the free list (immediate reuse — the continuous-batching payoff).
        ``scrub=True`` (NaN quarantine) marks the freed pages dirty so
        :meth:`_take_page` zeroes each one lazily on reuse; pages a fork
        sibling or the prefix cache still references are deferred via
        :meth:`_release_ref` — scrubbed only at refcount zero."""
        table = self._tables.pop(seq_id)
        self._lens.pop(seq_id)
        self._resv.pop(seq_id, None)
        for p in table:
            self._release_ref(p, scrub=scrub)
        self._refresh_gauges()

    def fork(self, src_id, dst_id, max_total_tokens: Optional[int] = None
             ) -> List[int]:
        """Fork ``src_id`` into ``dst_id`` sharing EVERY page by refcount
        — full pages and the partial tail alike. Nothing is copied at
        fork time: the first divergent append into the shared tail
        triggers copy-on-write (:meth:`extend`'s write guard), so a fork
        that never diverges (parallel scoring, n-best over a shared
        prompt) costs zero KV bytes. The substrate for prefix caching /
        parallel sampling."""
        if dst_id in self._tables:
            raise ValueError(f"sequence {dst_id!r} already allocated")
        src = self._tables[src_id]
        n = self._lens[src_id]
        table: List[int] = []
        for p in src:
            self._ref[p] += 1
            table.append(p)
        self._tables[dst_id] = table
        self._lens[dst_id] = n
        self._resv[dst_id] = self.pages_needed(
            max_total_tokens if max_total_tokens is not None else n)
        self.peak_used = max(self.peak_used, self.used_pages)
        return list(table)

    def _slot_coords(self, seq_id, n_tokens: int, start: int = 0):
        """(page_ids, offs) device coords of a sequence's KV slots
        ``start .. start+n_tokens-1`` — THE block-table indexing math,
        shared by every pool-rewrite path so it cannot drift between
        them."""
        table = np.asarray(self._tables[seq_id], np.int32)
        idx = np.arange(int(start), int(start) + int(n_tokens))
        return (jnp.asarray(table[idx // self.page_size]),
                jnp.asarray(idx % self.page_size))

    def poison_seq(self, seq_id, value: float = float("nan")) -> int:
        """Chaos helper (tests/test_faults.py, tools/chaos_serve.py):
        overwrite every EXCLUSIVELY-OWNED written KV slot of one sequence
        with ``value`` (default NaN), all layers, K and V. Shared pages
        (refcount > 1 — a fork sibling or the prefix cache holds them)
        are skipped: attention gathers shared bytes for REAL, so
        poisoning them would corrupt healthy tenants — a different drill
        than "this one sequence's KV went bad". Raises if the sequence
        has no exclusive written slots (the drill would silently no-op).
        Returns slots poisoned."""
        n = int(self._lens[seq_id])
        table = self._tables[seq_id]
        idx = np.arange(n)
        excl = self._ref[np.asarray(table, np.int32)[
            idx // self.page_size]] == 1
        idx = idx[excl]
        if idx.size == 0:
            raise ValueError(
                f"poison_seq({seq_id!r}): every written page is shared "
                f"(fork sibling or prefix cache holds a reference) — "
                f"poisoning would corrupt healthy tenants; poison a "
                f"sequence with exclusive pages instead")
        page_ids = jnp.asarray(
            np.asarray(table, np.int32)[idx // self.page_size])
        offs = jnp.asarray(idx % self.page_size)
        for li in range(self.num_layers):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(
                kp.at[page_ids, offs].set(jnp.asarray(value, kp.dtype)),
                stop_gradient=True)
            self.v_pools[li] = Tensor(
                vp.at[page_ids, offs].set(jnp.asarray(value, vp.dtype)),
                stop_gradient=True)
        return int(idx.size)

    # ------------------------------------------------------------- queries
    def has_seq(self, seq_id) -> bool:
        return seq_id in self._tables

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    def block_table_array(self, seq_ids: Sequence, width: int) -> np.ndarray:
        """Padded [len(seq_ids), width] int32 block-table batch; ``None``
        entries (idle slots) and table tails pad with the null page 0."""
        out = np.zeros((len(seq_ids), width), np.int32)
        for i, s in enumerate(seq_ids):
            if s is None:
                continue
            t = self._tables[s]
            if len(t) > width:
                raise ValueError(
                    f"sequence {s!r} spans {len(t)} pages > table width "
                    f"{width}")
            out[i, :len(t)] = t
        return out

    # ---------------------------------------------------------- cache hooks
    def attach_prefix_cache(self, cache: "PrefixCache") -> None:
        if self.prefix_cache is not None and self.prefix_cache is not cache:
            raise ValueError("pool already has a prefix cache attached")
        self.prefix_cache = cache

    # ------------------------------------------------------- device arrays
    def set_arrays(self, k_arrays, v_arrays) -> None:
        """Swap in the pools a compiled decode step returned (functional
        update — the engine's step owns the only in-flight copy)."""
        self.k_pools = [t if isinstance(t, Tensor)
                        else Tensor(t, stop_gradient=True)
                        for t in k_arrays]
        self.v_pools = [t if isinstance(t, Tensor)
                        else Tensor(t, stop_gradient=True)
                        for t in v_arrays]

    def write_prompt_kv(self, seq_id, layer_kv, start: int = 0) -> None:
        """Prefill's KV write hook: scatter a dense prompt cache into this
        sequence's pages at positions ``start .. start+S-1``. ``layer_kv``
        is a per-layer list of (k, v) arrays ``[S, n_kv_heads, head_dim]``
        (S = true token count; any padded prefill tail must already be
        sliced off). ``start`` > 0 is the prefix-cache suffix scatter:
        matched (shared) pages cover 0..start-1 and are never written —
        match granularity is full pages, so the suffix begins on a page
        this sequence owns."""
        s = int(layer_kv[0][0].shape[0])
        page_ids, offs = self._slot_coords(seq_id, s, start=start)
        for li, (k, v) in enumerate(layer_kv):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(
                kp.at[page_ids, offs].set(
                    jnp.asarray(k).astype(kp.dtype)), stop_gradient=True)
            self.v_pools[li] = Tensor(
                vp.at[page_ids, offs].set(
                    jnp.asarray(v).astype(vp.dtype)), stop_gradient=True)

    def gather_kv_range(self, page_ids: Sequence[int], n_tokens: int):
        """Read ``n_tokens`` of KV back out through a page list: per-layer
        list of (k, v) arrays ``[n_tokens, n_kv_heads, head_dim]`` — the
        prefix-cache hit path loads these into the suffix prefill's dense
        cache buffers (positions 0..n_tokens-1, already rope'd exactly as
        the original prefill wrote them)."""
        table = np.asarray(page_ids, np.int32)
        idx = np.arange(int(n_tokens))
        pages = jnp.asarray(table[idx // self.page_size])
        offs = jnp.asarray(idx % self.page_size)
        out = []
        for li in range(self.num_layers):
            out.append((self.k_pools[li]._value[pages, offs],
                        self.v_pools[li]._value[pages, offs]))
        return out

    def prefix_match_len(self, token_ids) -> int:
        """Read-only probe of the attached prefix cache (0 without one):
        tokens a live admission would adopt instead of prefilling — the
        scheduler charges its prefill budget with only the uncovered
        suffix (docs/SERVING.md "Prefix caching")."""
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.probe(token_ids)


class _PrefixNode:
    """One radix-tree edge = one FULL page of tokens. The path from the
    root to a node spells a token prefix (page_size tokens per hop); the
    node holds the page id whose KV covers that path's last page — KV at
    any position depends on every token before it (causal attention), so
    a page is reusable exactly when the WHOLE prefix matches, which is
    what keying each hop by its page's token bytes enforces."""

    __slots__ = ("key", "page", "parent", "children", "last_used",
                 "detached")

    def __init__(self, key: bytes, page: int, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[bytes, "_PrefixNode"] = {}
        self.last_used = 0
        self.detached = False


class PrefixCache:
    """Per-engine radix index over cached prompt prefixes → page lists.

    Built entirely on the pool's refcounts: every resident node holds ONE
    reference on its page, a live sequence that matched the node holds its
    own (via its block table), so a page is reclaimable exactly when the
    cache's reference is the last one. Admission calls :meth:`match` for
    the longest cached prefix (full-page granular, capped one token short
    of the prompt so there is always a suffix to prefill — the sample at
    position s-1 needs its logits computed), adopts the matched pages by
    refcount, ragged-prefills only the uncovered suffix, and
    :meth:`insert`\\ s its own full prompt pages for the next request.

    Eviction is LRU over unreferenced nodes, leaf-first (a pinned
    descendant pins nothing here: a sequence that matched a deep node
    holds refs on every page along the path, so an unpinned node's whole
    subtree is unpinned). The pool drives it from ``_take_page`` under
    pressure — the cache can never turn a coverable allocation into a
    failure — and the engine drives :meth:`evict_nodes` when a NaN
    quarantine makes a just-inserted prefix suspect.

    Telemetry ({engine_id, model_id} from the owning pool):
    ``paddle_tpu_serving_prefix_{hits,misses}_total``,
    ``paddle_tpu_serving_prefill_tokens_saved_total``,
    ``paddle_tpu_serving_prefix_cached_pages`` gauge,
    ``paddle_tpu_serving_prefix_evictions_total``.
    """

    def __init__(self, pool: PagedKVCachePool):
        self.pool = pool
        pool.attach_prefix_cache(self)
        self.page_size = pool.page_size
        self._root = _PrefixNode(b"", 0, None)
        # id-keyed for O(1) removal on eviction (a warm cache evicts on
        # the allocation hot path); _page_arr caches the resident page
        # ids for the vectorized reclaimable count, rebuilt lazily only
        # when the node set changes
        self._nodes: Dict[int, _PrefixNode] = {}
        self._page_arr: Optional[np.ndarray] = None
        self._clock = 0
        reg = metrics.get_registry()
        _eng = ("engine_id", "model_id")
        lbl = pool._lbl
        self._m_hits = reg.counter(
            "paddle_tpu_serving_prefix_hits_total",
            "Admissions that matched a cached prefix and prefilled only "
            "their uncovered suffix", labels=_eng).labels(**lbl)
        self._m_misses = reg.counter(
            "paddle_tpu_serving_prefix_misses_total",
            "Admissions that found no cached prefix (full prefill)",
            labels=_eng).labels(**lbl)
        self._m_saved = reg.counter(
            "paddle_tpu_serving_prefill_tokens_saved_total",
            "Prompt tokens NOT prefilled because a cached prefix covered "
            "them (the prefix-cache capacity win)",
            labels=_eng).labels(**lbl)
        self._m_pages = reg.gauge(
            "paddle_tpu_serving_prefix_cached_pages",
            "KV pages currently resident in the prefix cache (shared "
            "pages pinned by live sequences included)",
            labels=_eng).labels(**lbl)
        self._m_evictions = reg.counter(
            "paddle_tpu_serving_prefix_evictions_total",
            "Cache nodes evicted (LRU under pool pressure, or quarantine "
            "of a suspect prefix)", labels=_eng).labels(**lbl)
        self._m_pages.set(0)

    # -------------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._nodes)

    def reclaimable_pages(self) -> int:
        """Resident pages no live sequence references (pool refcount is
        exactly the cache's own) — what eviction can hand back. O(cache)
        per call; bounded by pool size."""
        if not self._nodes:
            return 0
        if self._page_arr is None:
            self._page_arr = np.fromiter(
                (n.page for n in self._nodes.values()), np.int32,
                len(self._nodes))
        return int(np.count_nonzero(self.pool._ref[self._page_arr] == 1))

    def _walk(self, ids: np.ndarray, touch: bool):
        """Longest-prefix walk: full pages only, capped at len(ids)-1
        tokens (at least one token must remain to prefill — its logits
        produce the first sample). Returns the node path."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        max_pages = max(int(ids.size) - 1, 0) // self.page_size
        path: List[_PrefixNode] = []
        cur = self._root
        for i in range(max_pages):
            key = ids[i * self.page_size:(i + 1) * self.page_size].tobytes()
            node = cur.children.get(key)
            if node is None:
                break
            path.append(node)
            cur = node
        if touch and path:
            self._clock += 1
            for n in path:
                n.last_used = self._clock
        return path

    def probe(self, ids) -> int:
        """Read-only match length in tokens (no LRU touch, no counters) —
        the scheduler's budget-honesty probe."""
        return len(self._walk(ids, touch=False)) * self.page_size

    def match(self, ids):
        """Longest cached prefix for ``ids``: (matched_tokens,
        page_ids, nodes). Touches LRU and moves the hit/miss counters;
        the caller adopts the pages by refcount via
        ``pool.allocate(..., prefix_pages=..., prefix_tokens=...)``."""
        path = self._walk(ids, touch=True)
        if not path:
            self._m_misses.inc()
            return 0, [], []
        self._m_hits.inc()
        matched = len(path) * self.page_size
        self._m_saved.inc(matched)
        return matched, [n.page for n in path], path

    # ------------------------------------------------------------ mutation
    def insert(self, ids, n_tokens: int, table: Sequence[int]
               ) -> List[_PrefixNode]:
        """Index every FULL page of ``ids[:n_tokens]`` (a just-prefilled
        prompt), taking one cache reference per NEWLY created node on the
        sequence's own page from ``table``. Pages whose prefix is already
        cached keep the existing node (and its page — the newcomer's
        private copy retires with it). Returns the nodes created here, in
        shallow-to-deep order (the engine journals them for quarantine
        eviction)."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        n_full = min(int(n_tokens), int(ids.size)) // self.page_size
        created: List[_PrefixNode] = []
        cur = self._root
        self._clock += 1
        for i in range(n_full):
            key = ids[i * self.page_size:(i + 1) * self.page_size].tobytes()
            node = cur.children.get(key)
            if node is None:
                node = _PrefixNode(key, int(table[i]), cur)
                cur.children[key] = node
                self.pool._ref[node.page] += 1
                self._nodes[id(node)] = node
                self._page_arr = None
                created.append(node)
            node.last_used = self._clock
            cur = node
        if created:
            self._m_pages.set(len(self._nodes))
            self.pool._refresh_gauges()
        return created

    def _detach(self, node: _PrefixNode, scrub: bool = False) -> bool:
        """Remove one childless node from the index and release the
        cache's page reference. Returns True when the page hit the free
        list (it may stay allocated: a live sequence still holds it)."""
        if node.detached:
            return False
        assert not node.children, "evicting a node with children"
        node.detached = True
        node.parent.children.pop(node.key, None)
        self._nodes.pop(id(node), None)
        self._page_arr = None
        freed = self.pool._release_ref(node.page, scrub=scrub)
        self._m_evictions.inc()
        self._m_pages.set(len(self._nodes))
        return freed

    def evict_one(self) -> bool:
        """LRU eviction step for ``_take_page`` under pool pressure:
        drop the least-recently-used unreferenced LEAF (leaf-first keeps
        the index consistent; an unpinned node's subtree is always
        unpinned, see class docstring). Returns True when a page was
        actually returned to the free list."""
        best: Optional[_PrefixNode] = None
        for n in self._nodes.values():
            if n.children or self.pool._ref[n.page] != 1:
                continue
            if best is None or n.last_used < best.last_used:
                best = n
        if best is None:
            return False
        freed = self._detach(best)
        self.pool._refresh_gauges()
        return freed

    def evict_nodes(self, nodes: Sequence[_PrefixNode]) -> None:
        """Quarantine eviction (engine's NaN path): drop these nodes AND
        their subtrees from the index — prefixes inserted from a
        poisoned request's KV, plus anything built on top of them, must
        never serve another admission. Pages pinned by live sequences
        stay allocated until those retire; the release is scrub-marked
        so a suspect page is zeroed before any reuse."""
        for node in nodes:
            self._evict_subtree(node, scrub=True)
        self.pool._refresh_gauges()

    def clear(self) -> int:
        """Flush the whole index (returns nodes evicted). REQUIRED after
        a weight change (``Router.reload``): cached KV was computed
        under the old weights, so a warm hit would mix stale prefix KV
        with new-weight suffix compute — silently wrong outputs. No
        scrub: stale-but-finite bytes are annihilated by attention masks
        like any retired page's."""
        n = len(self._nodes)
        for child in list(self._root.children.values()):
            self._evict_subtree(child, scrub=False)
        self.pool._refresh_gauges()
        return n

    def _evict_subtree(self, node: _PrefixNode, scrub: bool) -> None:
        if node.detached:
            return
        for child in list(node.children.values()):
            self._evict_subtree(child, scrub)
        self._detach(node, scrub=scrub)
