"""Paged KV-cache pool: fixed page pool + per-sequence block tables.

The serving engine's memory substrate (PAPERS.md: Ragged Paged Attention,
arxiv 2604.15464 — vLLM-style paging on TPU): instead of one dense
``[B, max_len, nkv, hd]`` cache per request, every layer owns a fixed pool
of ``[num_pages, page_size, n_kv_heads, head_dim]`` K and V blocks, and a
sequence is a *list of page ids* (its block table). Admission, retirement,
and fork never move KV bytes — only page ids change hands — so the decode
step's shapes stay fixed while the live batch churns.

Page 0 is the reserved NULL page: block tables are 0-padded and idle batch
slots carry all-zero tables, so their (masked) KV writes land harmlessly
there instead of corrupting a live sequence. The allocator hands out pages
1..num_pages-1.

Allocation is LAZY (a page is taken from the free list only when a token
actually lands in it) but admission is accounted against each sequence's
worst case via ``reserve`` — the scheduler admits a request only if the
pool can cover every live sequence's ``prompt + max_new_tokens`` tail, so
a mid-decode out-of-pages abort is impossible without preemption.

Sharding note (GSPMD, arxiv 2105.04663): the pool keeps the kv-head axis
third, matching the dense cache layout the mp mesh shards today — a later
multi-chip serving PR can shard ``n_kv_heads`` over 'mp' without touching
the allocator or block tables (page ids are replicated host metadata).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import faults, metrics
from ..tensor import Tensor

faults.declare_point(
    "serving.kv_alloc",
    "PagedKVCachePool._take_page, before a page leaves the free list — "
    "arm ResourceExhausted here to drill pool-exhaustion handling")

__all__ = ["PagedKVCachePool", "page_bytes", "pages_for_hbm_budget"]


def page_bytes(page_size: int, n_kv_heads: int, head_dim: int,
               num_layers: int, dtype_bytes: int = 4) -> int:
    """Bytes one page costs across ALL layers (K and V)."""
    return 2 * num_layers * page_size * n_kv_heads * head_dim * dtype_bytes


def pages_for_hbm_budget(hbm_bytes: int, page_size: int, n_kv_heads: int,
                         head_dim: int, num_layers: int,
                         dtype_bytes: int = 4) -> int:
    """Pool sizing math (docs/SERVING.md): pages = HBM budget / page bytes,
    minus nothing — the caller budgets weights/activations separately."""
    per = page_bytes(page_size, n_kv_heads, head_dim, num_layers, dtype_bytes)
    return max(int(hbm_bytes) // per, 0)


class PagedKVCachePool:
    """Fixed K/V page pool per layer + block-table allocator.

    Device state: ``k_pools``/``v_pools`` — one framework Tensor per layer,
    shape ``[num_pages, page_size, n_kv_heads, head_dim]``. The compiled
    decode step consumes and returns them functionally; the engine swaps
    the fresh arrays back in via :meth:`set_arrays`.

    Host state: free list, per-page refcounts (fork shares full pages
    copy-on-nothing — pages are append-only once full), per-sequence block
    tables and lengths, worst-case reservations, and the high-water mark
    (``peak_used``) the page-reuse tests assert on.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.float32,
                 engine_id: str = "", model_id: str = ""):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        # identity labels for the pool gauges: an engine passes its own
        # {engine_id, model_id} so N pools behind a Router stay N series
        # instead of last-writer-wins; a standalone pool reports under the
        # empty-string labels
        self._lbl = {"engine_id": str(engine_id), "model_id": str(model_id)}
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        shape = (self.num_pages, self.page_size, self.n_kv_heads,
                 self.head_dim)
        self.k_pools: List[Tensor] = [
            Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
            for _ in range(self.num_layers)]
        self.v_pools: List[Tensor] = [
            Tensor(jnp.zeros(shape, dtype), stop_gradient=True)
            for _ in range(self.num_layers)]
        # page 0 reserved: free list covers 1..num_pages-1 (LIFO for reuse
        # locality — a just-freed page is the next handed out)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._ref = np.zeros(self.num_pages, np.int32)
        # pages freed by a NaN quarantine: zeroed lazily the moment they
        # are re-taken (free() with scrub=True) — masked attention gives
        # padding lanes weight 0, but 0 x NaN = NaN, so a poisoned page
        # must never enter a new block table un-scrubbed. Lazy keeps the
        # quarantine itself O(1): no full-pool rewrite per retirement.
        self._dirty: set = set()
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        self._resv: Dict[object, int] = {}
        self.peak_used = 0
        reg = metrics.get_registry()
        _eng = ("engine_id", "model_id")
        self._m_pages_used = reg.gauge(
            "paddle_tpu_serving_kv_pages_used",
            "KV pages currently allocated out of the pool",
            labels=_eng).labels(**self._lbl)
        self._m_pages_total = reg.gauge(
            "paddle_tpu_serving_kv_pages_total",
            "Usable KV pages in the pool (page 0 reserved excluded)",
            labels=_eng).labels(**self._lbl)
        self._m_page_events = reg.counter(
            "paddle_tpu_serving_kv_page_events_total",
            "Page allocator traffic", labels=("event",) + _eng)
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        """Re-set BOTH pool gauges on every allocator event: the total is
        re-published (not just set once at construction) so a registry
        ``reset()`` mid-life self-heals instead of reporting 0 capacity
        forever. Each pool owns its {engine_id, model_id} series; the
        family-level read aggregates the fleet (docs/OBSERVABILITY.md)."""
        self._m_pages_used.set(self.used_pages)
        self._m_pages_total.set(self.usable_pages)

    # ---------------------------------------------------------- accounting
    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def used_pages(self) -> int:
        return self.usable_pages - len(self._free)

    def utilization(self) -> float:
        return self.used_pages / max(self.usable_pages, 1)

    def pages_needed(self, n_tokens: int) -> int:
        return max(math.ceil(int(n_tokens) / self.page_size), 1)

    def _unallocated_reserved(self) -> int:
        """Pages promised to live sequences but not yet drawn from the
        free list (their lazy tails)."""
        return sum(max(r - len(self._tables[s]), 0)
                   for s, r in self._resv.items())

    def can_admit(self, max_total_tokens: int,
                  pending_pages: int = 0) -> bool:
        """True when the pool can cover a new sequence's WORST CASE
        (``max_total_tokens`` = prompt + max_new_tokens) on top of every
        live sequence's outstanding reservation — the no-preemption
        admission guarantee. ``pending_pages`` charges pages promised to
        requests admitted earlier in the same scheduler step, whose
        reservations are not recorded here until their prefill runs."""
        need = self.pages_needed(max_total_tokens)
        return (need + int(pending_pages)
                <= len(self._free) - self._unallocated_reserved())

    # ---------------------------------------------------------- allocation
    def _take_page(self) -> int:
        faults.point("serving.kv_alloc")
        if not self._free:
            raise RuntimeError(
                "KV page pool exhausted — admission accounting should have "
                "prevented this (reserve() not called?)")
        p = self._free.pop()
        if p in self._dirty:
            # a quarantined page is about to re-enter a block table:
            # scrub ALL dirty pages in one batched update per layer
            # (each .at[].set copies the whole pool, so amortize the
            # copies over every pending page instead of paying them
            # per page)
            pages = jnp.asarray(sorted(self._dirty), jnp.int32)
            for li in range(self.num_layers):
                kp = self.k_pools[li]._value
                vp = self.v_pools[li]._value
                self.k_pools[li] = Tensor(
                    kp.at[pages].set(jnp.zeros((), kp.dtype)),
                    stop_gradient=True)
                self.v_pools[li] = Tensor(
                    vp.at[pages].set(jnp.zeros((), vp.dtype)),
                    stop_gradient=True)
            self._dirty.clear()
        self._ref[p] = 1
        self.peak_used = max(self.peak_used, self.used_pages)
        self._m_page_events.labels(event="alloc", **self._lbl).inc()
        self._refresh_gauges()
        return p

    def allocate(self, seq_id, n_tokens: int,
                 max_total_tokens: Optional[int] = None) -> List[int]:
        """Create a sequence holding ``n_tokens`` of KV (the prompt), with
        a worst-case reservation of ``max_total_tokens`` (defaults to
        ``n_tokens``). Returns the block table."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        resv = self.pages_needed(max_total_tokens
                                 if max_total_tokens is not None
                                 else n_tokens)
        self._tables[seq_id] = []
        self._lens[seq_id] = 0
        self._resv[seq_id] = resv
        try:
            self.extend(seq_id, n_tokens)
        except Exception:
            # atomic: a mid-allocate failure (real exhaustion or an armed
            # serving.kv_alloc fault) must not leak a half-built sequence —
            # roll back pages already taken and the bookkeeping entries
            self.free(seq_id)
            raise
        return list(self._tables[seq_id])

    def extend(self, seq_id, total_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``total_tokens`` of KV."""
        table = self._tables[seq_id]
        need = self.pages_needed(total_tokens)
        while len(table) < need:
            table.append(self._take_page())
        self._lens[seq_id] = max(self._lens[seq_id], int(total_tokens))

    def append_token(self, seq_id) -> None:
        """Make room for one more token (the engine calls this right before
        the decode step writes position ``seq_len``)."""
        self.extend(seq_id, self._lens[seq_id] + 1)

    def free(self, seq_id, scrub: bool = False) -> None:
        """Retire a sequence NOW: drop refcounts, return exclusive pages to
        the free list (immediate reuse — the continuous-batching payoff).
        ``scrub=True`` (NaN quarantine) marks the freed pages dirty so
        :meth:`_take_page` zeroes each one lazily on reuse."""
        table = self._tables.pop(seq_id)
        self._lens.pop(seq_id)
        self._resv.pop(seq_id, None)
        for p in table:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
                if scrub:
                    self._dirty.add(p)
                self._m_page_events.labels(event="free", **self._lbl).inc()
        self._refresh_gauges()

    def fork(self, src_id, dst_id, max_total_tokens: Optional[int] = None
             ) -> List[int]:
        """Fork ``src_id`` into ``dst_id`` sharing all FULL pages by
        refcount (they are append-only once full, so sharing is free); the
        partial tail page is copied into a fresh page so the two branches
        can diverge. The substrate for prefix caching / parallel sampling."""
        if dst_id in self._tables:
            raise ValueError(f"sequence {dst_id!r} already allocated")
        src = self._tables[src_id]
        n = self._lens[src_id]
        full = n // self.page_size  # pages completely written
        table: List[int] = []
        for p in src[:full]:
            self._ref[p] += 1
            table.append(p)
        if full < len(src):  # copy the partial tail
            tail = self._take_page()
            for i in range(self.num_layers):
                kv = self.k_pools[i]._value
                vv = self.v_pools[i]._value
                self.k_pools[i] = Tensor(
                    kv.at[tail].set(kv[src[full]]), stop_gradient=True)
                self.v_pools[i] = Tensor(
                    vv.at[tail].set(vv[src[full]]), stop_gradient=True)
            table.append(tail)
        self._tables[dst_id] = table
        self._lens[dst_id] = n
        self._resv[dst_id] = self.pages_needed(
            max_total_tokens if max_total_tokens is not None else n)
        self.peak_used = max(self.peak_used, self.used_pages)
        return list(table)

    def _slot_coords(self, seq_id, n_tokens: int):
        """(page_ids, offs) device coords of a sequence's first
        ``n_tokens`` KV slots — THE block-table indexing math, shared by
        every pool-rewrite path so it cannot drift between them."""
        table = np.asarray(self._tables[seq_id], np.int32)
        idx = np.arange(int(n_tokens))
        return (jnp.asarray(table[idx // self.page_size]),
                jnp.asarray(idx % self.page_size))

    def poison_seq(self, seq_id, value: float = float("nan")) -> int:
        """Chaos helper (tests/test_faults.py, tools/chaos_serve.py):
        overwrite every WRITTEN KV slot of one sequence with ``value``
        (default NaN), all layers, K and V. Because attention gathers
        strictly through block tables, the poison stays confined to this
        sequence — the engine's NaN quarantine must retire it while its
        batch-mates decode on untouched. Returns slots poisoned."""
        n = int(self._lens[seq_id])
        page_ids, offs = self._slot_coords(seq_id, n)
        for li in range(self.num_layers):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(
                kp.at[page_ids, offs].set(jnp.asarray(value, kp.dtype)),
                stop_gradient=True)
            self.v_pools[li] = Tensor(
                vp.at[page_ids, offs].set(jnp.asarray(value, vp.dtype)),
                stop_gradient=True)
        return n

    # ------------------------------------------------------------- queries
    def has_seq(self, seq_id) -> bool:
        return seq_id in self._tables

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    def block_table_array(self, seq_ids: Sequence, width: int) -> np.ndarray:
        """Padded [len(seq_ids), width] int32 block-table batch; ``None``
        entries (idle slots) and table tails pad with the null page 0."""
        out = np.zeros((len(seq_ids), width), np.int32)
        for i, s in enumerate(seq_ids):
            if s is None:
                continue
            t = self._tables[s]
            if len(t) > width:
                raise ValueError(
                    f"sequence {s!r} spans {len(t)} pages > table width "
                    f"{width}")
            out[i, :len(t)] = t
        return out

    # ------------------------------------------------------- device arrays
    def set_arrays(self, k_arrays, v_arrays) -> None:
        """Swap in the pools a compiled decode step returned (functional
        update — the engine's step owns the only in-flight copy)."""
        self.k_pools = [t if isinstance(t, Tensor)
                        else Tensor(t, stop_gradient=True)
                        for t in k_arrays]
        self.v_pools = [t if isinstance(t, Tensor)
                        else Tensor(t, stop_gradient=True)
                        for t in v_arrays]

    def write_prompt_kv(self, seq_id, layer_kv) -> None:
        """Prefill's KV write hook: scatter a dense prompt cache into this
        sequence's pages. ``layer_kv`` is a per-layer list of (k, v) arrays
        ``[S, n_kv_heads, head_dim]`` (S = true prompt length; any padded
        prefill tail must already be sliced off)."""
        s = int(layer_kv[0][0].shape[0])
        page_ids, offs = self._slot_coords(seq_id, s)
        for li, (k, v) in enumerate(layer_kv):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(
                kp.at[page_ids, offs].set(
                    jnp.asarray(k).astype(kp.dtype)), stop_gradient=True)
            self.v_pools[li] = Tensor(
                vp.at[page_ids, offs].set(
                    jnp.asarray(v).astype(vp.dtype)), stop_gradient=True)
