"""Paged KV-cache pool: fixed page pool + per-sequence block tables.

The serving engine's memory substrate (PAPERS.md: Ragged Paged Attention,
arxiv 2604.15464 — vLLM-style paging on TPU): instead of one dense
``[B, max_len, nkv, hd]`` cache per request, every layer owns a fixed pool
of ``[num_pages, page_size, n_kv_heads, head_dim]`` K and V blocks, and a
sequence is a *list of page ids* (its block table). Admission, retirement,
and fork never move KV bytes — only page ids change hands — so the decode
step's shapes stay fixed while the live batch churns.

Page 0 is the reserved NULL page: block tables are 0-padded and idle batch
slots carry all-zero tables, so their (masked) KV writes land harmlessly
there instead of corrupting a live sequence. The allocator hands out pages
1..num_pages-1.

Sharing is REFCOUNTED and copy-on-write: ``fork`` shares every page of
the source (full and partial tail alike) by bumping refcounts, and the
first divergent append into a shared page copies it lazily
(:meth:`extend`'s write guard) — the sibling's bytes are never mutated.
:class:`PrefixCache` builds on the same refcounts: a per-engine radix
index keyed on token ids maps cached prompt prefixes to page lists, so a
request sharing a system prompt adopts the cached pages at admission and
ragged-prefills only its uncovered suffix (docs/SERVING.md "Prefix
caching"). Cache-resident pages that no live sequence references are
RECLAIMABLE: they never cause an allocation failure — ``_take_page``
evicts LRU cache nodes under pool pressure — and they are excluded from
``used_pages`` (which counts pages live sequences pin).

Allocation is LAZY (a page is taken from the free list only when a token
actually lands in it) but admission is accounted against each sequence's
worst case via ``reserve`` — the scheduler admits a request only if the
pool can cover every live sequence's ``prompt + max_new_tokens`` tail, so
a mid-decode out-of-pages abort is impossible without preemption.

Sharding note (GSPMD, arxiv 2105.04663): the pool keeps the kv-head axis
third, matching the dense cache layout the mp mesh shards today — a later
multi-chip serving PR can shard ``n_kv_heads`` over 'mp' without touching
the allocator or block tables (page ids are replicated host metadata).

Page TIERS (docs/SERVING.md "KV page tiers & quantization"):

- **int8 pages** — ``PagedKVCachePool(dtype="int8")`` stores pages as
  int8 with per-slot f32 absmax scales (``k_scales``/``v_scales``,
  ``[num_pages, page_size, n_kv_heads]``; quantization/observers.py owns
  the scale rule). Writes quantize inside the compiled step; reads
  dequantize in-kernel (ops/pallas/paged_attention.py) — a full-width
  page never exists in HBM. Every allocator semantic treats a scale row
  as part of its page: CoW copies scales with bytes, lazy scrub zeroes
  both, poison lands in the SCALES (int8 cannot hold NaN; q × NaN = NaN
  through dequant), and fork/prefix adoption share scale rows for free
  because scales are page-indexed.
- **host tier** — :meth:`offload_seq` swaps a parked sequence's
  exclusively-owned written pages (bytes + scales, verbatim) into a
  host-RAM :class:`HostPageStore` and returns the HBM pages to the free
  list, ALSO releasing the sequence's unwritten-tail reservation — a
  parked tenant is a real preemption, so ``can_admit``/``used_pages``
  stay honest and admission prefers offload over rejection.
  :meth:`prefetch_seq` re-takes pages and scatters the saved bytes back
  bit-exactly BEFORE the slot's next step (the engine prefetches at
  unpark; the compiled step never blocks on a host→HBM copy).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .. import faults, metrics
from ..quantization.observers import (KV_SCALE_FLOOR, quantize_kv)
from ..tensor import Tensor

faults.declare_point(
    "serving.kv_alloc",
    "PagedKVCachePool._take_page, before a page leaves the free list — "
    "arm ResourceExhausted here to drill pool-exhaustion handling")

__all__ = ["PagedKVCachePool", "PrefixCache", "HostPageStore",
           "page_bytes", "pages_for_hbm_budget", "normalize_kv_dtype"]

_KV_DTYPE_ALIASES = {
    "f32": jnp.float32, "fp32": jnp.float32, "float32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "f16": jnp.float16, "fp16": jnp.float16, "float16": jnp.float16,
    "int8": jnp.int8,
}


def normalize_kv_dtype(dtype):
    """Resolve a KV-page dtype knob — a string alias (``"bf16"``,
    ``"int8"``, ...) or a jnp/np dtype — to the jnp dtype the pool
    stores. int8 means QUANTIZED pages (per-slot scales ride along)."""
    if isinstance(dtype, str):
        try:
            return _KV_DTYPE_ALIASES[dtype.lower()]
        except KeyError:
            raise ValueError(
                f"unknown kv_dtype {dtype!r}; expected one of "
                f"{sorted(_KV_DTYPE_ALIASES)}") from None
    return dtype


def page_bytes(page_size: int, n_kv_heads: int, head_dim: int,
               num_layers: int, dtype_bytes: int = None,
               kv_dtype=None) -> int:
    """HBM bytes one page costs across ALL layers (K and V). Pass
    ``kv_dtype`` to derive the element width from the pool's ACTUAL page
    dtype (bf16 → 2, int8 → 1 plus the 4-byte f32 scale each slot
    carries); ``dtype_bytes`` is the legacy scalar override (defaults to
    4 = f32) and ignores scale overhead."""
    if kv_dtype is not None:
        if dtype_bytes is not None:
            raise ValueError("pass kv_dtype or dtype_bytes, not both")
        dt = jnp.dtype(normalize_kv_dtype(kv_dtype))
        scale_bytes = 4 if dt == jnp.int8 else 0
        return (2 * num_layers * page_size * n_kv_heads
                * (head_dim * dt.itemsize + scale_bytes))
    if dtype_bytes is None:
        dtype_bytes = 4
    return 2 * num_layers * page_size * n_kv_heads * head_dim * dtype_bytes


def pages_for_hbm_budget(hbm_bytes: int, page_size: int, n_kv_heads: int,
                         head_dim: int, num_layers: int,
                         dtype_bytes: int = None, kv_dtype=None) -> int:
    """Pool sizing math (docs/SERVING.md): pages = HBM budget / page bytes,
    minus nothing — the caller budgets weights/activations separately.
    ``kv_dtype`` sizes against the real page dtype incl. scale overhead
    (the users/chip lever: int8 roughly halves bytes/page)."""
    per = page_bytes(page_size, n_kv_heads, head_dim, num_layers,
                     dtype_bytes=dtype_bytes, kv_dtype=kv_dtype)
    return max(int(hbm_bytes) // per, 0)


class HostPageStore:
    """Host-RAM second page tier: a dict of ``(seq_id, page_index) →``
    per-layer numpy slabs, written by :meth:`PagedKVCachePool.offload_seq`
    and drained by :meth:`prefetch_seq`. Bytes (and int8 scales) are
    stored verbatim — device→host→device round-trips are bit-exact by
    construction (the warm_equals_cold contract of the offload tier).
    Plain host memory, no device handles: survives pool array swaps and
    costs zero HBM."""

    def __init__(self):
        self._pages: Dict[tuple, dict] = {}

    def __len__(self) -> int:
        return len(self._pages)

    def put(self, seq_id, page_index: int, payload: dict) -> None:
        self._pages[(seq_id, int(page_index))] = payload

    def pop(self, seq_id, page_index: int) -> dict:
        return self._pages.pop((seq_id, int(page_index)))

    def seq_pages(self, seq_id) -> List[int]:
        return sorted(pi for (s, pi) in self._pages if s == seq_id)

    def drop_seq(self, seq_id) -> int:
        """Discard a retiring sequence's host copies (no device writes —
        there is nothing to scrub: host bytes never enter a gather)."""
        keys = [k for k in self._pages if k[0] == seq_id]
        for k in keys:
            del self._pages[k]
        return len(keys)


class PagedKVCachePool:
    """Fixed K/V page pool per layer + block-table allocator.

    Device state: ``k_pools``/``v_pools`` — one framework Tensor per layer,
    shape ``[num_pages, page_size, n_kv_heads, head_dim]``. The compiled
    decode step consumes and returns them functionally; the engine swaps
    the fresh arrays back in via :meth:`set_arrays`.

    Host state: free list, per-page refcounts (fork shares full pages
    copy-on-nothing — pages are append-only once full), per-sequence block
    tables and lengths, worst-case reservations, and the high-water mark
    (``peak_used``) the page-reuse tests assert on.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.float32,
                 engine_id: str = "", model_id: str = ""):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        # identity labels for the pool gauges: an engine passes its own
        # {engine_id, model_id} so N pools behind a Router stay N series
        # instead of last-writer-wins; a standalone pool reports under the
        # empty-string labels
        self._lbl = {"engine_id": str(engine_id), "model_id": str(model_id)}
        self.num_layers = int(num_layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = normalize_kv_dtype(dtype)
        # int8 pages carry per-slot f32 absmax scales (module docstring,
        # "Page TIERS"); every page-granular allocator path below mirrors
        # its byte operation onto the scale arrays
        self.quantized = jnp.dtype(self.dtype) == jnp.int8
        shape = (self.num_pages, self.page_size, self.n_kv_heads,
                 self.head_dim)
        self.k_pools: List[Tensor] = [
            Tensor(jnp.zeros(shape, self.dtype), stop_gradient=True)
            for _ in range(self.num_layers)]
        self.v_pools: List[Tensor] = [
            Tensor(jnp.zeros(shape, self.dtype), stop_gradient=True)
            for _ in range(self.num_layers)]
        if self.quantized:
            sshape = shape[:3]  # [num_pages, page_size, n_kv_heads]
            self.k_scales: Optional[List[Tensor]] = [
                Tensor(jnp.zeros(sshape, jnp.float32), stop_gradient=True)
                for _ in range(self.num_layers)]
            self.v_scales: Optional[List[Tensor]] = [
                Tensor(jnp.zeros(sshape, jnp.float32), stop_gradient=True)
                for _ in range(self.num_layers)]
        else:
            self.k_scales = None
            self.v_scales = None
        # host offload tier: parked sequences' page bytes live here while
        # their HBM pages serve other tenants; _host_idx maps seq_id →
        # set of offloaded page indices (their table entries hold the
        # null-page sentinel 0), _parked_resv journals the tail
        # reservation released while parked
        self.host_store = HostPageStore()
        self._host_idx: Dict[object, set] = {}
        self._parked_resv: Dict[object, int] = {}
        # page 0 reserved: free list covers 1..num_pages-1 (LIFO for reuse
        # locality — a just-freed page is the next handed out)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._ref = np.zeros(self.num_pages, np.int32)
        # pages freed by a NaN quarantine: zeroed lazily the moment they
        # are re-taken (free() with scrub=True) — masked attention gives
        # padding lanes weight 0, but 0 x NaN = NaN, so a poisoned page
        # must never enter a new block table un-scrubbed. Lazy keeps the
        # quarantine itself O(1): no full-pool rewrite per retirement.
        self._dirty: set = set()
        # refcount-aware deferred scrub (docs/RESILIENCE.md "Quarantine x
        # refcounts"): a quarantined victim's free(scrub=True) must NOT
        # zero a page a sibling fork / the prefix cache still reads —
        # such pages are only MARKED here, and the mark converts to a
        # real scrub when the LAST reference drops (whoever drops it),
        # so a suspect page can never re-enter circulation un-scrubbed.
        self._scrub_pending: set = set()
        # optional per-engine prefix cache; PrefixCache attaches itself
        self.prefix_cache: Optional["PrefixCache"] = None
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        self._resv: Dict[object, int] = {}
        self.peak_used = 0
        reg = metrics.get_registry()
        _eng = ("engine_id", "model_id")
        self._m_pages_used = reg.gauge(
            "paddle_tpu_serving_kv_pages_used",
            "KV pages currently allocated out of the pool",
            labels=_eng).labels(**self._lbl)
        self._m_pages_total = reg.gauge(
            "paddle_tpu_serving_kv_pages_total",
            "Usable KV pages in the pool (page 0 reserved excluded)",
            labels=_eng).labels(**self._lbl)
        self._m_page_events = reg.counter(
            "paddle_tpu_serving_kv_page_events_total",
            "Page allocator traffic", labels=("event",) + _eng)
        _tier = reg.gauge(
            "paddle_tpu_serving_kv_page_tier",
            "KV pages currently resident per tier: hbm = pages pinned by "
            "live sequences, host = pages parked in the HostPageStore",
            labels=("tier",) + _eng)
        self._m_tier_hbm = _tier.labels(tier="hbm", **self._lbl)
        self._m_tier_host = _tier.labels(tier="host", **self._lbl)
        self._m_offload = reg.counter(
            "paddle_tpu_serving_kv_offload_pages_total",
            "KV pages swapped HBM → host by offload_seq (parked tenants)",
            labels=_eng).labels(**self._lbl)
        self._m_prefetch = reg.counter(
            "paddle_tpu_serving_kv_prefetch_pages_total",
            "KV pages swapped host → HBM by prefetch_seq (unpark)",
            labels=_eng).labels(**self._lbl)
        self._m_scale_clips = reg.counter(
            "paddle_tpu_serving_kv_dequant_scale_clip_total",
            "Quantized KV slots written at the absmax scale floor "
            "(absmax underflowed KV_SCALE_FLOOR — dynamic range lost)",
            labels=_eng).labels(**self._lbl)
        self._refresh_gauges()

    def _refresh_gauges(self) -> None:
        """Re-set the pool gauges on every allocator event: the totals are
        re-published (not just set once at construction) so a registry
        ``reset()`` mid-life self-heals instead of reporting 0 capacity
        forever. Each pool owns its {engine_id, model_id} series; the
        family-level read aggregates the fleet (docs/OBSERVABILITY.md)."""
        self._m_pages_used.set(self.used_pages)
        self._m_pages_total.set(self.usable_pages)
        self._m_tier_hbm.set(self.used_pages)
        self._m_tier_host.set(len(self.host_store))

    # ---------------------------------------------------------- accounting
    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1

    @property
    def used_pages(self) -> int:
        """Pages pinned by LIVE sequences. Cache-resident pages no
        sequence references are excluded: they are reclaimable on demand
        (evict-then-retry in :meth:`_take_page`), so counting them as
        used would make a warm cache read as pressure it isn't."""
        return (self.usable_pages - len(self._free)
                - self._reclaimable_pages())

    def _reclaimable_pages(self) -> int:
        """Pages held ONLY by the prefix cache — evictable the moment an
        allocation needs them."""
        return (self.prefix_cache.reclaimable_pages()
                if self.prefix_cache is not None else 0)

    def utilization(self) -> float:
        return self.used_pages / max(self.usable_pages, 1)

    def pages_needed(self, n_tokens: int) -> int:
        return max(math.ceil(int(n_tokens) / self.page_size), 1)

    def _unallocated_reserved(self) -> int:
        """Pages promised to live sequences but not yet drawn from the
        free list (their lazy tails)."""
        return sum(max(r - len(self._tables[s]), 0)
                   for s, r in self._resv.items())

    def can_admit(self, max_total_tokens: int,
                  pending_pages: int = 0, cached_pages: int = 0,
                  pending_cached: int = 0) -> bool:
        """True when the pool can cover a new sequence's WORST CASE
        (``max_total_tokens`` = prompt + max_new_tokens) on top of every
        live sequence's outstanding reservation — the no-preemption
        admission guarantee. ``pending_pages`` charges pages promised to
        requests admitted earlier in the same scheduler step, whose
        reservations are not recorded here until their prefill runs.
        ``cached_pages`` discounts pages the prefix cache already holds
        for this request's prompt (they join its table by refcount, not
        by a free-list draw). Matched pages must ALSO leave the
        reclaimable side: the moment the request adopts them their
        refcount pins them, so counting them both as "not needed" and as
        "evictable for someone else" would double-count and overcommit —
        the victim being some LIVE sequence's reserved tail.
        ``pending_cached`` extends the same exclusion to pages matched
        by earlier same-step admissions (conservative when two
        batch-mates match the SAME pages: under-admission just waits a
        step; overcommit kills a tenant)."""
        need = self.pages_needed(max_total_tokens) - int(cached_pages)
        reclaim = max(self._reclaimable_pages() - int(cached_pages)
                      - int(pending_cached), 0)
        avail = len(self._free) + reclaim - self._unallocated_reserved()
        return need + int(pending_pages) <= avail

    # ---------------------------------------------------------- allocation
    def _take_page(self) -> int:
        faults.point("serving.kv_alloc")
        # cache-never-starves-tenants: under pool pressure, evict LRU
        # unreferenced prefix-cache nodes until a page frees — the cache
        # must never turn a coverable allocation into a failure
        while not self._free and self.prefix_cache is not None:
            if not self.prefix_cache.evict_one():
                break
        if not self._free:
            raise RuntimeError(
                "KV page pool exhausted — admission accounting should have "
                "prevented this (reserve() not called?)")
        p = self._free.pop()
        if p in self._dirty:
            # a quarantined page is about to re-enter a block table:
            # scrub ALL dirty pages in one batched update per layer
            # (each .at[].set copies the whole pool, so amortize the
            # copies over every pending page instead of paying them
            # per page)
            pages = jnp.asarray(sorted(self._dirty), jnp.int32)
            for li in range(self.num_layers):
                kp = self.k_pools[li]._value
                vp = self.v_pools[li]._value
                self.k_pools[li] = Tensor(
                    kp.at[pages].set(jnp.zeros((), kp.dtype)),
                    stop_gradient=True)
                self.v_pools[li] = Tensor(
                    vp.at[pages].set(jnp.zeros((), vp.dtype)),
                    stop_gradient=True)
                if self.quantized:
                    # poison lives in the SCALE rows on int8 pools —
                    # scrub them with the page bytes
                    ks = self.k_scales[li]._value
                    vs = self.v_scales[li]._value
                    self.k_scales[li] = Tensor(
                        ks.at[pages].set(jnp.zeros((), ks.dtype)),
                        stop_gradient=True)
                    self.v_scales[li] = Tensor(
                        vs.at[pages].set(jnp.zeros((), vs.dtype)),
                        stop_gradient=True)
            self._dirty.clear()
        self._ref[p] = 1
        self.peak_used = max(self.peak_used, self.used_pages)
        self._m_page_events.labels(event="alloc", **self._lbl).inc()
        self._refresh_gauges()
        return p

    def allocate(self, seq_id, n_tokens: int,
                 max_total_tokens: Optional[int] = None,
                 prefix_pages: Sequence[int] = (),
                 prefix_tokens: int = 0) -> List[int]:
        """Create a sequence holding ``n_tokens`` of KV (the prompt), with
        a worst-case reservation of ``max_total_tokens`` (defaults to
        ``n_tokens``). Returns the block table.

        ``prefix_pages``/``prefix_tokens`` seed the table with SHARED
        pages (a prefix-cache hit): each is adopted by refcount — no
        free-list draw, no KV copy — and the prefix refs are bumped
        BEFORE any fresh page is taken, so a mid-allocate eviction can
        never reclaim the very pages this sequence is adopting. Rollback
        (:meth:`free`) drops shared and fresh pages uniformly."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        if prefix_tokens and int(prefix_tokens) % self.page_size:
            raise ValueError(
                f"prefix_tokens {prefix_tokens} must be page-aligned "
                f"(page_size={self.page_size}) — prefix sharing is "
                f"full-page granular")
        resv = self.pages_needed(max_total_tokens
                                 if max_total_tokens is not None
                                 else n_tokens)
        table: List[int] = []
        for p in prefix_pages:
            self._ref[p] += 1
            table.append(p)
        self._tables[seq_id] = table
        self._lens[seq_id] = int(prefix_tokens)
        self._resv[seq_id] = resv
        if int(n_tokens) > int(prefix_tokens):
            try:
                self.extend(seq_id, n_tokens)
            except Exception:
                # atomic: a mid-allocate failure (real exhaustion or an
                # armed serving.kv_alloc fault) must not leak a half-built
                # sequence — roll back pages already taken and the
                # bookkeeping entries
                self.free(seq_id)
                raise
        # n_tokens == prefix_tokens is the chunked-prefill admission
        # path: the sequence starts as EXACTLY its adopted prefix (zero
        # fresh pages, zero writable-page checks — the next write lands
        # at position prefix_tokens, a page this table doesn't hold yet,
        # so CoW-copying the shared tail page here would only break the
        # sharing the adoption just paid for)
        self.peak_used = max(self.peak_used, self.used_pages)
        return list(self._tables[seq_id])

    def extend(self, seq_id, total_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``total_tokens`` of KV, and
        guarantee the LAST slot (the one about to be written) lives in a
        page this sequence owns exclusively — the copy-on-write seam: a
        fork/prefix-share diverging into a shared page copies it here,
        first, so the sibling's (and the cache's) bytes are immutable."""
        self._assert_resident(seq_id, "extend")
        table = self._tables[seq_id]
        need = self.pages_needed(total_tokens)
        while len(table) < need:
            table.append(self._take_page())
        self._lens[seq_id] = max(self._lens[seq_id], int(total_tokens))
        self._ensure_writable(seq_id, int(total_tokens) - 1)

    def extend_write(self, seq_id, start: int, total_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``total_tokens`` of KV and
        make EVERY page holding positions ``start .. total_tokens-1``
        exclusively owned — the multi-token variant of :meth:`extend`'s
        one-slot CoW seam. A unified-step prompt chunk scatters a whole
        token range in one compiled program, so any page it touches that
        a fork sibling or the prefix cache still references must be
        copied first (freshly drawn pages are exclusive by construction;
        in practice only the range's FIRST page can be shared — a
        partially written fork tail)."""
        start, total = int(start), int(total_tokens)
        if total <= start:
            return
        self._assert_resident(seq_id, "extend_write")
        table = self._tables[seq_id]
        need = self.pages_needed(total)
        while len(table) < need:
            table.append(self._take_page())
        self._lens[seq_id] = max(self._lens[seq_id], total)
        for pi in range(start // self.page_size,
                        (total - 1) // self.page_size + 1):
            self._ensure_page_writable(seq_id, pi)

    def truncate(self, seq_id, total_tokens: int) -> None:
        """Roll ``seq_id``'s KV length back to ``total_tokens`` — the
        speculative-decoding reject path: draft rows past the accepted
        prefix wrote KV for tokens that were never committed, and
        lowering ``_lens`` is ALL the rollback there is. The pages stay
        in the table (they sit inside the admission-time reservation, so
        nothing else can claim them) and their stale bytes are inert:
        paged attention masks every row at its own position, so KV past
        the sequence length is never gathered, and the next committed
        write at those positions scatters right over it. Refcounts are
        untouched — the rejected range was already made exclusively
        owned by the :meth:`extend_write` that reserved it, and a CoW'd
        page stays correctly owned for the retry."""
        total = int(total_tokens)
        cur = self._lens[seq_id]
        if total < 0 or total > cur:
            raise ValueError(
                f"truncate({seq_id!r}, {total}) outside [0, {cur}] — "
                f"rollback can only shorten a sequence")
        self._lens[seq_id] = total

    def _ensure_writable(self, seq_id, token_pos: int) -> None:
        """Copy-on-write: if the page holding ``token_pos`` is shared
        (refcount > 1 — a fork sibling or the prefix cache also holds
        it), copy its contents into a fresh page and swap the block-table
        entry, leaving the shared original untouched."""
        if token_pos < 0:
            return
        self._ensure_page_writable(seq_id, token_pos // self.page_size)

    def _ensure_page_writable(self, seq_id, pi: int) -> None:
        """CoW one block-table entry by page index (the shared seam of
        :meth:`extend` and :meth:`extend_write`)."""
        table = self._tables[seq_id]
        old = table[pi]
        if self._ref[old] <= 1:
            return
        fresh = self._take_page()
        for li in range(self.num_layers):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(kp.at[fresh].set(kp[old]),
                                      stop_gradient=True)
            self.v_pools[li] = Tensor(vp.at[fresh].set(vp[old]),
                                      stop_gradient=True)
            if self.quantized:
                # CoW copies SCALES with pages — a sibling diverging into
                # a shared int8 page must not rescale the original's slots
                ks = self.k_scales[li]._value
                vs = self.v_scales[li]._value
                self.k_scales[li] = Tensor(ks.at[fresh].set(ks[old]),
                                           stop_gradient=True)
                self.v_scales[li] = Tensor(vs.at[fresh].set(vs[old]),
                                           stop_gradient=True)
        table[pi] = fresh
        # the shared original loses OUR reference only (cannot hit zero:
        # ref was > 1); scrub state, if any, stays with the original
        self._ref[old] -= 1
        self._m_page_events.labels(event="cow", **self._lbl).inc()
        self.peak_used = max(self.peak_used, self.used_pages)
        self._refresh_gauges()

    def append_token(self, seq_id) -> None:
        """Make room for one more token (the engine calls this right before
        the decode step writes position ``seq_len``)."""
        self.extend(seq_id, self._lens[seq_id] + 1)

    def _release_ref(self, p: int, scrub: bool = False) -> bool:
        """Drop ONE reference on page ``p`` (the single choreography every
        release path — sequence retirement, cache eviction — goes
        through, so scrub semantics cannot drift between them). Returns
        True when the page actually hit the free list.

        Refcount-aware scrub: a ``scrub=True`` release while siblings
        still hold the page must neither zero it now (a healthy tenant
        is reading those bytes) nor forget it — the page is marked
        scrub-pending, and WHOEVER drops the last reference (even a
        normal ``scrub=False`` retirement, even a cache eviction)
        converts the mark into a real lazy scrub before reuse."""
        self._ref[p] -= 1
        if self._ref[p] > 0:
            if scrub:
                self._scrub_pending.add(p)
            return False
        self._free.append(p)
        if scrub or p in self._scrub_pending:
            self._dirty.add(p)
        self._scrub_pending.discard(p)
        self._m_page_events.labels(event="free", **self._lbl).inc()
        return True

    def free(self, seq_id, scrub: bool = False) -> None:
        """Retire a sequence NOW: drop refcounts, return exclusive pages to
        the free list (immediate reuse — the continuous-batching payoff).
        ``scrub=True`` (NaN quarantine) marks the freed pages dirty so
        :meth:`_take_page` zeroes each one lazily on reuse; pages a fork
        sibling or the prefix cache still references are deferred via
        :meth:`_release_ref` — scrubbed only at refcount zero.

        A sequence retiring with OFFLOADED pages (parked, then cancelled
        or deadline-swept, or exported for migration) drops its host
        copies without any device write: those table entries hold the
        null-page sentinel — their HBM pages were already released at
        offload time — so releasing them again would corrupt page 0's
        refcount. Host bytes never enter a gather, so there is nothing
        to scrub on that tier (docs/RESILIENCE.md)."""
        table = self._tables.pop(seq_id)
        self._lens.pop(seq_id)
        self._resv.pop(seq_id, None)
        self._parked_resv.pop(seq_id, None)
        off = self._host_idx.pop(seq_id, ())
        self.host_store.drop_seq(seq_id)
        for pi, p in enumerate(table):
            if pi in off:
                continue
            self._release_ref(p, scrub=scrub)
        self._refresh_gauges()

    def fork(self, src_id, dst_id, max_total_tokens: Optional[int] = None
             ) -> List[int]:
        """Fork ``src_id`` into ``dst_id`` sharing EVERY page by refcount
        — full pages and the partial tail alike. Nothing is copied at
        fork time: the first divergent append into the shared tail
        triggers copy-on-write (:meth:`extend`'s write guard), so a fork
        that never diverges (parallel scoring, n-best over a shared
        prompt) costs zero KV bytes. The substrate for prefix caching /
        parallel sampling."""
        if dst_id in self._tables:
            raise ValueError(f"sequence {dst_id!r} already allocated")
        self._assert_resident(src_id, "fork")
        src = self._tables[src_id]
        n = self._lens[src_id]
        table: List[int] = []
        for p in src:
            self._ref[p] += 1
            table.append(p)
        self._tables[dst_id] = table
        self._lens[dst_id] = n
        self._resv[dst_id] = self.pages_needed(
            max_total_tokens if max_total_tokens is not None else n)
        self.peak_used = max(self.peak_used, self.used_pages)
        return list(table)

    # ------------------------------------------------------- host tier
    def _assert_resident(self, seq_id, op: str) -> None:
        """Writes, forks, and poison require every page in HBM — an
        offloaded table entry is the null-page sentinel 0, so touching it
        would read/write the reserved page. The engine upholds this by
        excluding parked slots from the step grid and prefetching at
        unpark; this guard turns a policy bug into a loud error instead
        of silent corruption."""
        if self._host_idx.get(seq_id):
            raise RuntimeError(
                f"{op}({seq_id!r}): sequence has "
                f"{len(self._host_idx[seq_id])} offloaded page(s) — "
                f"prefetch_seq() must restore them first")

    def offloaded_pages(self, seq_id=None) -> int:
        """Pages currently parked on the host tier — for one sequence, or
        pool-wide with ``seq_id=None``."""
        if seq_id is not None:
            return len(self._host_idx.get(seq_id, ()))
        return len(self.host_store)

    def spare_pages(self) -> int:
        """Pages the pool could hand out RIGHT NOW without breaking any
        live sequence's reservation: free + cache-reclaimable − promised
        lazy tails. The engine's park/unpark policy reasons in this
        currency (admit the queue head, re-admit a parked tenant)."""
        return (len(self._free) + self._reclaimable_pages()
                - self._unallocated_reserved())

    def can_prefetch(self, seq_id) -> bool:
        """True when :meth:`prefetch_seq` can restore ``seq_id`` AND
        re-assume its worst-case tail reservation without overcommitting
        — unpark is an admission in reverse, held to the same
        no-preemption arithmetic as :meth:`can_admit`."""
        off = self._host_idx.get(seq_id)
        if not off:
            return True
        tail = max(self._parked_resv.get(seq_id, 0)
                   - len(self._tables[seq_id]), 0)
        return len(off) + tail <= self.spare_pages()

    def prefetch_cost(self, seq_id) -> int:
        """Pages :meth:`prefetch_seq` would charge against
        :meth:`spare_pages` — offloaded pages to restore plus the
        journaled tail reservation to re-assume. The engine's anti-thrash
        check subtracts this before unparking so the queue head's next
        admission is never displaced by the tenant it preempted."""
        off = self._host_idx.get(seq_id)
        if not off:
            return 0
        tail = max(self._parked_resv.get(seq_id, 0)
                   - len(self._tables[seq_id]), 0)
        return len(off) + tail

    def offload_seq(self, seq_id) -> int:
        """Swap ``seq_id``'s exclusively-owned written pages to the host
        tier (bytes + int8 scales, verbatim — the round-trip is
        bit-exact) and release BOTH the HBM pages and the sequence's
        unwritten-tail reservation. Shared pages (prefix cache / fork
        siblings hold them) stay resident: other tenants gather them for
        real. Returns pages moved; idempotent on a parked sequence.

        Capacity honesty: freed pages land on the free list, the tail
        reservation is journaled into ``_parked_resv`` and zeroed, so
        ``can_admit`` sees a parked tenant as fully preempted — the
        eviction order "offload before prefix-evict" follows because the
        engine parks victims BEFORE any allocation walks
        :meth:`_take_page`'s cache-eviction loop."""
        table = self._tables[seq_id]
        n = int(self._lens[seq_id])
        off = self._host_idx.setdefault(seq_id, set())
        written = self.pages_needed(n) if n > 0 else 0
        move = [pi for pi in range(min(written, len(table)))
                if pi not in off and self._ref[table[pi]] == 1]
        if seq_id not in self._parked_resv:
            self._parked_resv[seq_id] = self._resv.get(seq_id, 0)
            self._resv[seq_id] = 0
        if move:
            pages = jnp.asarray(np.asarray([table[pi] for pi in move],
                                           np.int32))
            for pi in move:
                payload = {"k": [], "v": []}
                if self.quantized:
                    payload["ks"], payload["vs"] = [], []
                self.host_store.put(seq_id, pi, payload)
            # one gather per layer per array, then split per page — the
            # device→host copy happens HERE (park time, off the step
            # path), never inside a compiled step
            for li in range(self.num_layers):
                kslab = np.asarray(self.k_pools[li]._value[pages])
                vslab = np.asarray(self.v_pools[li]._value[pages])
                for j, pi in enumerate(move):
                    pl = self.host_store._pages[(seq_id, pi)]
                    pl["k"].append(kslab[j])
                    pl["v"].append(vslab[j])
                if self.quantized:
                    ksc = np.asarray(self.k_scales[li]._value[pages])
                    vsc = np.asarray(self.v_scales[li]._value[pages])
                    for j, pi in enumerate(move):
                        pl = self.host_store._pages[(seq_id, pi)]
                        pl["ks"].append(ksc[j])
                        pl["vs"].append(vsc[j])
            for pi in move:
                off.add(pi)
                self._release_ref(table[pi])
                table[pi] = 0
            self._m_offload.inc(len(move))
            self._m_page_events.labels(event="offload", **self._lbl).inc(
                len(move))
        self._refresh_gauges()
        return len(move)

    def prefetch_seq(self, seq_id) -> int:
        """Restore every offloaded page of ``seq_id`` into freshly drawn
        HBM pages (bytes + scales scattered back verbatim → bit-exact)
        and re-assume the journaled tail reservation. All-or-nothing: if
        the pool cannot cover the restore, pages taken so far return to
        the free list and the sequence stays parked. The engine calls
        this at UNPARK, before the slot re-enters the step grid — the
        compiled step itself never waits on a host→HBM copy."""
        off = self._host_idx.get(seq_id)
        if not off:
            # nothing on the host tier; still restore a journaled tail
            # reservation (a park that moved zero pages — all shared)
            if seq_id in self._parked_resv:
                self._resv[seq_id] = max(self._parked_resv.pop(seq_id),
                                         self._resv.get(seq_id, 0))
            return 0
        table = self._tables[seq_id]
        idxs = sorted(off)
        fresh: List[int] = []
        try:
            for _ in idxs:
                fresh.append(self._take_page())
        except Exception:
            for p in fresh:
                self._release_ref(p)
            self._refresh_gauges()
            raise
        pages = jnp.asarray(np.asarray(fresh, np.int32))
        payloads = [self.host_store.pop(seq_id, pi) for pi in idxs]
        for li in range(self.num_layers):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            kslab = jnp.asarray(np.stack([p["k"][li] for p in payloads]))
            vslab = jnp.asarray(np.stack([p["v"][li] for p in payloads]))
            self.k_pools[li] = Tensor(kp.at[pages].set(kslab),
                                      stop_gradient=True)
            self.v_pools[li] = Tensor(vp.at[pages].set(vslab),
                                      stop_gradient=True)
            if self.quantized:
                ks = self.k_scales[li]._value
                vs = self.v_scales[li]._value
                kssl = jnp.asarray(np.stack([p["ks"][li]
                                             for p in payloads]))
                vssl = jnp.asarray(np.stack([p["vs"][li]
                                             for p in payloads]))
                self.k_scales[li] = Tensor(ks.at[pages].set(kssl),
                                           stop_gradient=True)
                self.v_scales[li] = Tensor(vs.at[pages].set(vssl),
                                           stop_gradient=True)
        for pi, p in zip(idxs, fresh):
            table[pi] = p
        self._host_idx.pop(seq_id, None)
        if seq_id in self._parked_resv:
            self._resv[seq_id] = max(self._parked_resv.pop(seq_id),
                                     self._resv.get(seq_id, 0))
        self._m_prefetch.inc(len(idxs))
        self._m_page_events.labels(event="prefetch", **self._lbl).inc(
            len(idxs))
        self.peak_used = max(self.peak_used, self.used_pages)
        self._refresh_gauges()
        return len(idxs)

    def record_scale_clips(self, page_ids, offs) -> int:
        """Count this step's written slots whose absmax scale clamped at
        KV_SCALE_FLOOR (all layers, K and V) and move the
        ``kv_dequant_scale_clip_total`` counter. The engine calls this
        with the step's (page, offset) coords right after the program
        returns — a floor-clamped slot quantized with its dynamic range
        collapsed (absmax underflow), the one int8 failure mode absmax
        scaling cannot round away (docs/OBSERVABILITY.md)."""
        if not self.quantized or len(page_ids) == 0:
            return 0
        pages = jnp.asarray(np.asarray(page_ids, np.int32))
        oo = jnp.asarray(np.asarray(offs, np.int32))
        floor = jnp.float32(KV_SCALE_FLOOR)
        n = 0
        for li in range(self.num_layers):
            n += int(jnp.sum(
                self.k_scales[li]._value[pages, oo] <= floor))
            n += int(jnp.sum(
                self.v_scales[li]._value[pages, oo] <= floor))
        if n:
            self._m_scale_clips.inc(n)
        return n

    def _slot_coords(self, seq_id, n_tokens: int, start: int = 0):
        """(page_ids, offs) device coords of a sequence's KV slots
        ``start .. start+n_tokens-1`` — THE block-table indexing math,
        shared by every pool-rewrite path so it cannot drift between
        them."""
        table = np.asarray(self._tables[seq_id], np.int32)
        idx = np.arange(int(start), int(start) + int(n_tokens))
        return (jnp.asarray(table[idx // self.page_size]),
                jnp.asarray(idx % self.page_size))

    def poison_seq(self, seq_id, value: float = float("nan")) -> int:
        """Chaos helper (tests/test_faults.py, tools/chaos_serve.py):
        overwrite every EXCLUSIVELY-OWNED written KV slot of one sequence
        with ``value`` (default NaN), all layers, K and V. Shared pages
        (refcount > 1 — a fork sibling or the prefix cache holds them)
        are skipped: attention gathers shared bytes for REAL, so
        poisoning them would corrupt healthy tenants — a different drill
        than "this one sequence's KV went bad". Raises if the sequence
        has no exclusive written slots (the drill would silently no-op).
        Returns slots poisoned.

        int8 pools poison the SCALE rows instead of the page bytes: an
        int8 slot cannot hold NaN, but ``q × NaN = NaN`` through the
        in-kernel dequant, so a poisoned scale contaminates attention
        exactly like a poisoned bf16 slot would — and the lazy scrub
        zeroes scale rows with their pages (:meth:`_take_page`)."""
        self._assert_resident(seq_id, "poison_seq")
        n = int(self._lens[seq_id])
        table = self._tables[seq_id]
        idx = np.arange(n)
        excl = self._ref[np.asarray(table, np.int32)[
            idx // self.page_size]] == 1
        idx = idx[excl]
        if idx.size == 0:
            raise ValueError(
                f"poison_seq({seq_id!r}): every written page is shared "
                f"(fork sibling or prefix cache holds a reference) — "
                f"poisoning would corrupt healthy tenants; poison a "
                f"sequence with exclusive pages instead")
        page_ids = jnp.asarray(
            np.asarray(table, np.int32)[idx // self.page_size])
        offs = jnp.asarray(idx % self.page_size)
        if self.quantized:
            for li in range(self.num_layers):
                ks = self.k_scales[li]._value
                vs = self.v_scales[li]._value
                self.k_scales[li] = Tensor(
                    ks.at[page_ids, offs].set(
                        jnp.asarray(value, ks.dtype)), stop_gradient=True)
                self.v_scales[li] = Tensor(
                    vs.at[page_ids, offs].set(
                        jnp.asarray(value, vs.dtype)), stop_gradient=True)
            return int(idx.size)
        for li in range(self.num_layers):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            self.k_pools[li] = Tensor(
                kp.at[page_ids, offs].set(jnp.asarray(value, kp.dtype)),
                stop_gradient=True)
            self.v_pools[li] = Tensor(
                vp.at[page_ids, offs].set(jnp.asarray(value, vp.dtype)),
                stop_gradient=True)
        return int(idx.size)

    # ------------------------------------------------------------- queries
    def has_seq(self, seq_id) -> bool:
        return seq_id in self._tables

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def block_table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    def block_table_array(self, seq_ids: Sequence, width: int) -> np.ndarray:
        """Padded [len(seq_ids), width] int32 block-table batch; ``None``
        entries (idle slots) and table tails pad with the null page 0."""
        out = np.zeros((len(seq_ids), width), np.int32)
        for i, s in enumerate(seq_ids):
            if s is None:
                continue
            t = self._tables[s]
            if len(t) > width:
                raise ValueError(
                    f"sequence {s!r} spans {len(t)} pages > table width "
                    f"{width}")
            out[i, :len(t)] = t
        return out

    # ---------------------------------------------------------- cache hooks
    def attach_prefix_cache(self, cache: "PrefixCache") -> None:
        if self.prefix_cache is not None and self.prefix_cache is not cache:
            raise ValueError("pool already has a prefix cache attached")
        self.prefix_cache = cache

    # ------------------------------------------------------- device arrays
    def set_arrays(self, k_arrays, v_arrays, k_scales=None,
                   v_scales=None) -> None:
        """Swap in the pools a compiled decode step returned (functional
        update — the engine's step owns the only in-flight copy). A
        quantized pool's step also returns the updated scale arrays."""
        self.k_pools = [t if isinstance(t, Tensor)
                        else Tensor(t, stop_gradient=True)
                        for t in k_arrays]
        self.v_pools = [t if isinstance(t, Tensor)
                        else Tensor(t, stop_gradient=True)
                        for t in v_arrays]
        if k_scales is not None:
            self.k_scales = [t if isinstance(t, Tensor)
                             else Tensor(t, stop_gradient=True)
                             for t in k_scales]
            self.v_scales = [t if isinstance(t, Tensor)
                             else Tensor(t, stop_gradient=True)
                             for t in v_scales]

    @property
    def step_stride(self) -> int:
        """Device arrays one layer contributes to the compiled step's
        flat cache operands: (k, v) or (k, v, k_scale, v_scale)."""
        return 4 if self.quantized else 2

    def step_arrays(self, li: int):
        """Layer ``li``'s cache tuple in step-operand order — the single
        definition both the engine's program invocation and its
        result-unpacking use, so the stride cannot drift."""
        if self.quantized:
            return (self.k_pools[li], self.v_pools[li],
                    self.k_scales[li], self.v_scales[li])
        return (self.k_pools[li], self.v_pools[li])

    def set_step_flat(self, flat) -> None:
        """Inverse of per-layer :meth:`step_arrays` concatenation: accept
        the compiled step's flat cache outputs and swap every array (and
        scale array, when quantized) back in."""
        s = self.step_stride
        self.set_arrays(
            [flat[s * i] for i in range(self.num_layers)],
            [flat[s * i + 1] for i in range(self.num_layers)],
            k_scales=([flat[s * i + 2] for i in range(self.num_layers)]
                      if self.quantized else None),
            v_scales=([flat[s * i + 3] for i in range(self.num_layers)]
                      if self.quantized else None))

    def write_prompt_kv(self, seq_id, layer_kv, start: int = 0) -> None:
        """Prefill's KV write hook: scatter a dense prompt cache into this
        sequence's pages at positions ``start .. start+S-1``. ``layer_kv``
        is a per-layer list of (k, v) arrays ``[S, n_kv_heads, head_dim]``
        (S = true token count; any padded prefill tail must already be
        sliced off). ``start`` > 0 is the prefix-cache suffix scatter:
        matched (shared) pages cover 0..start-1 and are never written —
        match granularity is full pages, so the suffix begins on a page
        this sequence owns. Quantized pools quantize here (per-slot
        absmax, quantization/observers.py) and scatter values + scales —
        the same grid the in-step scatter writes, so prefill-written and
        decode-written slots dequantize identically."""
        self._assert_resident(seq_id, "write_prompt_kv")
        s = int(layer_kv[0][0].shape[0])
        page_ids, offs = self._slot_coords(seq_id, s, start=start)
        for li, (k, v) in enumerate(layer_kv):
            kp = self.k_pools[li]._value
            vp = self.v_pools[li]._value
            if self.quantized:
                kq, ksc = quantize_kv(jnp.asarray(k))
                vq, vsc = quantize_kv(jnp.asarray(v))
                self.k_pools[li] = Tensor(
                    kp.at[page_ids, offs].set(kq), stop_gradient=True)
                self.v_pools[li] = Tensor(
                    vp.at[page_ids, offs].set(vq), stop_gradient=True)
                ks = self.k_scales[li]._value
                vs = self.v_scales[li]._value
                self.k_scales[li] = Tensor(
                    ks.at[page_ids, offs].set(ksc), stop_gradient=True)
                self.v_scales[li] = Tensor(
                    vs.at[page_ids, offs].set(vsc), stop_gradient=True)
                continue
            self.k_pools[li] = Tensor(
                kp.at[page_ids, offs].set(
                    jnp.asarray(k).astype(kp.dtype)), stop_gradient=True)
            self.v_pools[li] = Tensor(
                vp.at[page_ids, offs].set(
                    jnp.asarray(v).astype(vp.dtype)), stop_gradient=True)
        if self.quantized:
            self.record_scale_clips(np.asarray(page_ids),
                                    np.asarray(offs))

    def gather_kv_range(self, page_ids: Sequence[int], n_tokens: int):
        """Read ``n_tokens`` of KV back out through a page list: per-layer
        list of (k, v) arrays ``[n_tokens, n_kv_heads, head_dim]`` — the
        prefix-cache hit path loads these into the suffix prefill's dense
        cache buffers (positions 0..n_tokens-1, already rope'd exactly as
        the original prefill wrote them). Quantized pools return the
        DEQUANTIZED f32 values (toleranced, like quantized attention
        itself) — callers consume values, not codes."""
        table = np.asarray(page_ids, np.int32)
        idx = np.arange(int(n_tokens))
        pages = jnp.asarray(table[idx // self.page_size])
        offs = jnp.asarray(idx % self.page_size)
        out = []
        for li in range(self.num_layers):
            k = self.k_pools[li]._value[pages, offs]
            v = self.v_pools[li]._value[pages, offs]
            if self.quantized:
                k = (k.astype(jnp.float32)
                     * self.k_scales[li]._value[pages, offs][..., None])
                v = (v.astype(jnp.float32)
                     * self.v_scales[li]._value[pages, offs][..., None])
            out.append((k, v))
        return out

    def prefix_match_len(self, token_ids) -> int:
        """Read-only probe of the attached prefix cache (0 without one):
        tokens a live admission would adopt instead of prefilling — the
        scheduler charges its prefill budget with only the uncovered
        suffix (docs/SERVING.md "Prefix caching")."""
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.probe(token_ids)


class _PrefixNode:
    """One radix-tree edge = one FULL page of tokens. The path from the
    root to a node spells a token prefix (page_size tokens per hop); the
    node holds the page id whose KV covers that path's last page — KV at
    any position depends on every token before it (causal attention), so
    a page is reusable exactly when the WHOLE prefix matches, which is
    what keying each hop by its page's token bytes enforces."""

    __slots__ = ("key", "page", "parent", "children", "last_used",
                 "detached")

    def __init__(self, key: bytes, page: int, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[bytes, "_PrefixNode"] = {}
        self.last_used = 0
        self.detached = False


class PrefixCache:
    """Per-engine radix index over cached prompt prefixes → page lists.

    Built entirely on the pool's refcounts: every resident node holds ONE
    reference on its page, a live sequence that matched the node holds its
    own (via its block table), so a page is reclaimable exactly when the
    cache's reference is the last one. Admission calls :meth:`match` for
    the longest cached prefix (full-page granular, capped one token short
    of the prompt so there is always a suffix to prefill — the sample at
    position s-1 needs its logits computed), adopts the matched pages by
    refcount, ragged-prefills only the uncovered suffix, and
    :meth:`insert`\\ s its own full prompt pages for the next request.

    Eviction is LRU over unreferenced nodes, leaf-first (a pinned
    descendant pins nothing here: a sequence that matched a deep node
    holds refs on every page along the path, so an unpinned node's whole
    subtree is unpinned). The pool drives it from ``_take_page`` under
    pressure — the cache can never turn a coverable allocation into a
    failure — and the engine drives :meth:`evict_nodes` when a NaN
    quarantine makes a just-inserted prefix suspect.

    Telemetry ({engine_id, model_id} from the owning pool):
    ``paddle_tpu_serving_prefix_{hits,misses}_total``,
    ``paddle_tpu_serving_prefill_tokens_saved_total``,
    ``paddle_tpu_serving_prefix_cached_pages`` gauge,
    ``paddle_tpu_serving_prefix_evictions_total``.
    """

    def __init__(self, pool: PagedKVCachePool):
        self.pool = pool
        pool.attach_prefix_cache(self)
        self.page_size = pool.page_size
        self._root = _PrefixNode(b"", 0, None)
        # id-keyed for O(1) removal on eviction (a warm cache evicts on
        # the allocation hot path); _page_arr caches the resident page
        # ids for the vectorized reclaimable count, rebuilt lazily only
        # when the node set changes
        self._nodes: Dict[int, _PrefixNode] = {}
        self._page_arr: Optional[np.ndarray] = None
        self._clock = 0
        reg = metrics.get_registry()
        _eng = ("engine_id", "model_id")
        lbl = pool._lbl
        self._m_hits = reg.counter(
            "paddle_tpu_serving_prefix_hits_total",
            "Admissions that matched a cached prefix and prefilled only "
            "their uncovered suffix", labels=_eng).labels(**lbl)
        self._m_misses = reg.counter(
            "paddle_tpu_serving_prefix_misses_total",
            "Admissions that found no cached prefix (full prefill)",
            labels=_eng).labels(**lbl)
        self._m_saved = reg.counter(
            "paddle_tpu_serving_prefill_tokens_saved_total",
            "Prompt tokens NOT prefilled because a cached prefix covered "
            "them (the prefix-cache capacity win)",
            labels=_eng).labels(**lbl)
        self._m_pages = reg.gauge(
            "paddle_tpu_serving_prefix_cached_pages",
            "KV pages currently resident in the prefix cache (shared "
            "pages pinned by live sequences included)",
            labels=_eng).labels(**lbl)
        self._m_evictions = reg.counter(
            "paddle_tpu_serving_prefix_evictions_total",
            "Cache nodes evicted (LRU under pool pressure, or quarantine "
            "of a suspect prefix)", labels=_eng).labels(**lbl)
        self._m_pages.set(0)

    # -------------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._nodes)

    def reclaimable_pages(self) -> int:
        """Resident pages no live sequence references (pool refcount is
        exactly the cache's own) — what eviction can hand back. O(cache)
        per call; bounded by pool size."""
        if not self._nodes:
            return 0
        if self._page_arr is None:
            self._page_arr = np.fromiter(
                (n.page for n in self._nodes.values()), np.int32,
                len(self._nodes))
        return int(np.count_nonzero(self.pool._ref[self._page_arr] == 1))

    def _walk(self, ids: np.ndarray, touch: bool):
        """Longest-prefix walk: full pages only, capped at len(ids)-1
        tokens (at least one token must remain to prefill — its logits
        produce the first sample). Returns the node path."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        max_pages = max(int(ids.size) - 1, 0) // self.page_size
        path: List[_PrefixNode] = []
        cur = self._root
        for i in range(max_pages):
            key = ids[i * self.page_size:(i + 1) * self.page_size].tobytes()
            node = cur.children.get(key)
            if node is None:
                break
            path.append(node)
            cur = node
        if touch and path:
            self._clock += 1
            for n in path:
                n.last_used = self._clock
        return path

    def probe(self, ids) -> int:
        """Read-only match length in tokens (no LRU touch, no counters) —
        the scheduler's budget-honesty probe."""
        return len(self._walk(ids, touch=False)) * self.page_size

    def match(self, ids):
        """Longest cached prefix for ``ids``: (matched_tokens,
        page_ids, nodes). Touches LRU and moves the hit/miss counters;
        the caller adopts the pages by refcount via
        ``pool.allocate(..., prefix_pages=..., prefix_tokens=...)``."""
        path = self._walk(ids, touch=True)
        if not path:
            self._m_misses.inc()
            return 0, [], []
        self._m_hits.inc()
        matched = len(path) * self.page_size
        self._m_saved.inc(matched)
        return matched, [n.page for n in path], path

    # ------------------------------------------------------------ mutation
    def insert(self, ids, n_tokens: int, table: Sequence[int]
               ) -> List[_PrefixNode]:
        """Index every FULL page of ``ids[:n_tokens]`` (a just-prefilled
        prompt), taking one cache reference per NEWLY created node on the
        sequence's own page from ``table``. Pages whose prefix is already
        cached keep the existing node (and its page — the newcomer's
        private copy retires with it). Returns the nodes created here, in
        shallow-to-deep order (the engine journals them for quarantine
        eviction)."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        n_full = min(int(n_tokens), int(ids.size)) // self.page_size
        created: List[_PrefixNode] = []
        cur = self._root
        self._clock += 1
        for i in range(n_full):
            key = ids[i * self.page_size:(i + 1) * self.page_size].tobytes()
            node = cur.children.get(key)
            if node is None:
                node = _PrefixNode(key, int(table[i]), cur)
                cur.children[key] = node
                self.pool._ref[node.page] += 1
                self._nodes[id(node)] = node
                self._page_arr = None
                created.append(node)
            node.last_used = self._clock
            cur = node
        if created:
            self._m_pages.set(len(self._nodes))
            self.pool._refresh_gauges()
        return created

    def _detach(self, node: _PrefixNode, scrub: bool = False) -> bool:
        """Remove one childless node from the index and release the
        cache's page reference. Returns True when the page hit the free
        list (it may stay allocated: a live sequence still holds it)."""
        if node.detached:
            return False
        assert not node.children, "evicting a node with children"
        node.detached = True
        node.parent.children.pop(node.key, None)
        self._nodes.pop(id(node), None)
        self._page_arr = None
        freed = self.pool._release_ref(node.page, scrub=scrub)
        self._m_evictions.inc()
        self._m_pages.set(len(self._nodes))
        return freed

    def evict_one(self) -> bool:
        """LRU eviction step for ``_take_page`` under pool pressure:
        drop the least-recently-used unreferenced LEAF (leaf-first keeps
        the index consistent; an unpinned node's subtree is always
        unpinned, see class docstring). Returns True when a page was
        actually returned to the free list."""
        best: Optional[_PrefixNode] = None
        for n in self._nodes.values():
            if n.children or self.pool._ref[n.page] != 1:
                continue
            if best is None or n.last_used < best.last_used:
                best = n
        if best is None:
            return False
        freed = self._detach(best)
        self.pool._refresh_gauges()
        return freed

    def evict_nodes(self, nodes: Sequence[_PrefixNode]) -> None:
        """Quarantine eviction (engine's NaN path): drop these nodes AND
        their subtrees from the index — prefixes inserted from a
        poisoned request's KV, plus anything built on top of them, must
        never serve another admission. Pages pinned by live sequences
        stay allocated until those retire; the release is scrub-marked
        so a suspect page is zeroed before any reuse."""
        for node in nodes:
            self._evict_subtree(node, scrub=True)
        self.pool._refresh_gauges()

    def clear(self) -> int:
        """Flush the whole index (returns nodes evicted). REQUIRED after
        a weight change (``Router.reload``): cached KV was computed
        under the old weights, so a warm hit would mix stale prefix KV
        with new-weight suffix compute — silently wrong outputs. No
        scrub: stale-but-finite bytes are annihilated by attention masks
        like any retired page's."""
        n = len(self._nodes)
        for child in list(self._root.children.values()):
            self._evict_subtree(child, scrub=False)
        self.pool._refresh_gauges()
        return n

    def _evict_subtree(self, node: _PrefixNode, scrub: bool) -> None:
        if node.detached:
            return
        for child in list(node.children.values()):
            self._evict_subtree(child, scrub)
        self._detach(node, scrub=scrub)
