"""Host-side draft proposers for speculative decoding on the unified step.

Speculative decoding (docs/SERVING.md "Speculative decoding") needs a
cheap guess at the next few tokens of a stream; the engine then scores
the guesses as EXTRA ROWS of the very same compiled ragged step it runs
anyway, accepts the matching prefix, and rolls the KV length back over
the rest. The drafter is pure host-side numpy — it never touches the
compiled program, so speculation adds ZERO compiled signatures.

:class:`NGramDrafter` is the reference-free baseline (the "prompt
lookup" family): the best predictor of a stream that repeats itself is
the stream itself. It suffix-matches the last ``n`` tokens of the
request's (prompt + generated) ids against every earlier occurrence and
proposes the continuation of the LATEST match. Great on code, quoting,
templated text, and any decode loop that has settled into a cycle;
harmless elsewhere — a wrong draft costs one discarded grid row, never
a wrong token (acceptance is exact-match against the per-position
sampled targets, see the engine's determinism contract).

Custom drafters only need ``propose(ids, k) -> np.ndarray`` (up to ``k``
int32 draft tokens, possibly empty); the engine treats the proposal as
untrusted either way.
"""
from __future__ import annotations

import numpy as np

__all__ = ["NGramDrafter"]

_EMPTY = np.empty(0, np.int32)


class NGramDrafter:
    """Propose draft tokens by n-gram suffix match over the stream itself.

    ``max_ngram`` bounds the match length tried (longest first — a longer
    matched suffix is stronger evidence the continuation will repeat);
    ``min_ngram`` the shortest worth acting on. ``k`` is the default
    proposal cap; the engine passes its own per-call cap (budget- and
    length-limited) which takes precedence.
    """

    def __init__(self, k: int = 4, max_ngram: int = 3, min_ngram: int = 1):
        self.k = int(k)
        self.max_ngram = max(int(max_ngram), 1)
        self.min_ngram = max(int(min_ngram), 1)
        if self.min_ngram > self.max_ngram:
            raise ValueError(
                f"min_ngram {self.min_ngram} > max_ngram {self.max_ngram}")

    def propose(self, ids: np.ndarray, k: int | None = None) -> np.ndarray:
        """Up to ``k`` draft tokens continuing ``ids`` (the request's
        prompt + generated stream), or an empty array when no suffix of
        length >= min_ngram recurs. Pure and stateless: proposals depend
        only on ``ids``, so a migrated request drafts identically on its
        adoptive engine."""
        k = self.k if k is None else int(k)
        ids = np.asarray(ids, np.int32).reshape(-1)
        n_total = ids.size
        if k <= 0 or n_total < self.min_ngram + 1:
            return _EMPTY
        for n in range(min(self.max_ngram, n_total - 1),
                       self.min_ngram - 1, -1):
            suffix = ids[n_total - n:]
            # all length-n windows that could be followed by >= 1 token
            windows = np.lib.stride_tricks.sliding_window_view(
                ids[:n_total - 1], n)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            if hits.size == 0:
                continue
            # the LATEST earlier occurrence: recent context beats stale
            start = int(hits[-1]) + n
            return ids[start:start + k].copy()
        return _EMPTY
