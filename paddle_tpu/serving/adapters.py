"""Batched multi-LoRA serving: stacked rank-r adapter weights that ride
the unified serving step as DATA (ISSUE 16).

An :class:`AdapterStore` holds up to ``capacity`` named LoRA adapters
for every projection site the trunk exposes (``model.lora_sites()``),
stacked along a leading adapter axis::

    A[site]: [capacity, n_layers, rank, in_dim ]
    B[site]: [capacity, n_layers, out_dim, rank]

Slot 0 is RESERVED as the zero-delta identity: its weights are all
zeros, so a request with no adapter (``adapter_id=None`` → slot 0)
computes ``base(x) + B0 @ (A0 @ x) == base(x) + 0`` — bit-identical to
a store-less engine. Registration is a pure VALUE write
(``.at[slot].set(...)``): shapes never change, so the compiled step —
which takes the stacked arrays as arguments and gathers each grid
row's adapter by index — never recompiles. That is the whole trick:
like seeds (PR 7), chunk rows (PR 11), and draft rows (PR 14), tenancy
is data, not program (docs/SERVING.md "Multi-LoRA adapters").
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

__all__ = ["AdapterStore", "random_adapter", "lora_delta"]


def lora_delta(x, A, B, layer: int):
    """The fused per-row LoRA delta, applied inside the compiled step:
    ``delta[t] = B[t, layer] @ (A[t, layer] @ x[t])`` where ``A``/``B``
    are the PER-ROW gathered stacks (``[T, L, rank, in]`` /
    ``[T, L, out, rank]``) and ``layer`` is a Python constant baked into
    the trace. Rows pointing at slot 0 contribute exactly zero — the
    bit-identity guarantee for non-adapter tenants. One traced op per
    site per layer; XLA fuses the two small einsums into the
    surrounding projection."""
    from ..ops._apply import apply_op, ensure_tensor

    def fn(xv, av, bv):
        al = av[:, layer]                       # [T, rank, in]
        bl = bv[:, layer]                       # [T, out, rank]
        h = jnp.einsum("tri,tsi->tsr", al, xv.astype(al.dtype))
        return jnp.einsum("tor,tsr->tso", bl, h).astype(xv.dtype)

    return apply_op(fn, [ensure_tensor(x), ensure_tensor(A),
                         ensure_tensor(B)], name="lora_delta")


class AdapterStore:
    """Named rank-r LoRA (A, B) pairs, stacked per projection site.

    ``sites`` is an ordered sequence of ``(name, in_dim, out_dim)``
    triples — one entry per projection the trunk offers a delta at,
    shared across layers (the layer axis is inside each array). The
    fixed site order is the contract with the compiled step:
    :meth:`arrays` flattens ``[A, B]`` per site in exactly this order,
    every step, whether or not any adapter is registered.
    """

    def __init__(self, sites: Sequence[Tuple[str, int, int]],
                 num_layers: int, rank: int = 4, capacity: int = 4,
                 dtype=jnp.float32):
        if capacity < 2:
            raise ValueError(
                f"capacity must be >= 2 (slot 0 is the reserved "
                f"zero-delta identity), got {capacity}")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.sites = tuple((str(n), int(i), int(o)) for n, i, o in sites)
        if not self.sites:
            raise ValueError("at least one projection site is required")
        self.num_layers = int(num_layers)
        self.rank = int(rank)
        self.capacity = int(capacity)
        self.dtype = dtype
        self._A: Dict[str, jnp.ndarray] = {}
        self._B: Dict[str, jnp.ndarray] = {}
        for name, d_in, d_out in self.sites:
            self._A[name] = jnp.zeros(
                (self.capacity, self.num_layers, self.rank, d_in), dtype)
            self._B[name] = jnp.zeros(
                (self.capacity, self.num_layers, d_out, self.rank), dtype)
        # slot 0 is the identity and is never in this map
        self._slots: Dict[str, int] = {}

    @classmethod
    def from_model(cls, model, rank: int = 4, capacity: int = 4,
                   dtype=jnp.float32) -> "AdapterStore":
        """Build a store shaped for ``model`` via its ``lora_sites()``
        contract: ``(sites, num_layers)`` with sites as
        ``(name, in_dim, out_dim)`` triples in trunk order."""
        sites, num_layers = model.lora_sites()
        return cls(sites, num_layers, rank=rank, capacity=capacity,
                   dtype=dtype)

    # ------------------------------------------------------------ registry
    def register(self, name: str, weights: Dict[str, tuple]) -> int:
        """Install (or hot-swap) adapter ``name``: ``weights`` maps each
        site name to an ``(A, B)`` pair with shapes
        ``[n_layers, rank, in_dim]`` / ``[n_layers, out_dim, rank]``.
        Every site must be present (a site with no delta is all-zero —
        explicitness beats a silent partial adapter). Returns the slot.

        The write is ``.at[slot].set(value)`` per array: same shapes,
        same dtypes — the compiled step that consumes these arrays is
        untouched, which is what makes fleet-wide hot-load recompile-
        free (``compile_counts()`` pins it)."""
        if name is None or name == "":
            raise ValueError("adapter name must be a non-empty string "
                             "(None means 'no adapter', slot 0)")
        missing = [s for s, _, _ in self.sites if s not in weights]
        if missing:
            raise ValueError(
                f"adapter {name!r} missing sites {missing}; provide an "
                "all-zero (A, B) pair for sites without a delta")
        slot = self._slots.get(name)
        if slot is None:
            used = set(self._slots.values())
            free = [s for s in range(1, self.capacity) if s not in used]
            if not free:
                raise ValueError(
                    f"adapter store full ({self.capacity - 1} slots, "
                    f"holding {sorted(self._slots)}); unregister one or "
                    "raise adapter_capacity")
            slot = free[0]
        staged = []
        for site, d_in, d_out in self.sites:
            A, B = weights[site]
            A = np.asarray(A, self.dtype)
            B = np.asarray(B, self.dtype)
            want_a = (self.num_layers, self.rank, d_in)
            want_b = (self.num_layers, d_out, self.rank)
            if A.shape != want_a or B.shape != want_b:
                raise ValueError(
                    f"adapter {name!r} site {site!r}: expected A "
                    f"{want_a} / B {want_b}, got {A.shape} / {B.shape}")
            staged.append((site, A, B))
        # validate-then-write: a bad site above must not leave a
        # half-installed adapter behind
        for site, A, B in staged:
            self._A[site] = self._A[site].at[slot].set(A)
            self._B[site] = self._B[site].at[slot].set(B)
        self._slots[name] = slot
        return slot

    def unregister(self, name: str) -> None:
        """Zero the adapter's slot and free it. The zero write means a
        stale index racing the unregister degrades to the identity
        delta, never another tenant's weights."""
        slot = self._slots.pop(name)
        for site, _, _ in self.sites:
            self._A[site] = self._A[site].at[slot].set(0.0)
            self._B[site] = self._B[site].at[slot].set(0.0)

    # ------------------------------------------------------------- lookups
    def slot(self, name: Optional[str]) -> int:
        """``name`` → stacked-array index; ``None`` is the identity."""
        if name is None:
            return 0
        slot = self._slots.get(name)
        if slot is None:
            raise KeyError(
                f"adapter {name!r} not registered here (holding "
                f"{sorted(self._slots)})")
        return slot

    def holds(self, name: Optional[str]) -> bool:
        """True iff this store can serve ``name`` — what Router's
        ``select()`` filters placement on. Every store holds ``None``."""
        return name is None or name in self._slots

    def names(self) -> Tuple[str, ...]:
        return tuple(sorted(self._slots))

    def arrays(self) -> List[jnp.ndarray]:
        """The step's adapter arguments: ``[A, B]`` per site in the
        fixed site order — stable length and shapes for the life of the
        engine."""
        out: List[jnp.ndarray] = []
        for site, _, _ in self.sites:
            out.append(self._A[site])
            out.append(self._B[site])
        return out

    def __repr__(self) -> str:
        return (f"AdapterStore(sites={len(self.sites)}, "
                f"layers={self.num_layers}, rank={self.rank}, "
                f"capacity={self.capacity}, holding={list(self.names())})")


def random_adapter(store: AdapterStore, seed: int,
                   scale: float = 0.02) -> Dict[str, tuple]:
    """A seeded random weight dict shaped for ``store`` — tests, the
    bench drill, and the metrics demo all need *some* non-zero adapter;
    ``scale`` keeps the delta small enough that tiny models stay
    finite."""
    rng = np.random.default_rng(seed)
    out: Dict[str, tuple] = {}
    for site, d_in, d_out in store.sites:
        A = rng.standard_normal(
            (store.num_layers, store.rank, d_in)).astype(np.float32)
        B = rng.standard_normal(
            (store.num_layers, d_out, store.rank)).astype(np.float32)
        out[site] = (A * scale, B * scale)
    return out
