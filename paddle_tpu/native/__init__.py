"""Native (C++) runtime components and their build/load machinery.

The reference keeps its runtime core in C++ (store/rpc/PS tables under
``paddle/fluid/distributed``, ``paddle/phi/core/distributed/store``);
here the native pieces are compiled on first use with the in-image
toolchain (g++) into ``paddle_tpu/native/lib`` and bound via ctypes —
this image has no pybind11, and ctypes keeps the ABI surface explicit.

``load_library("tcp_store")`` compiles ``src/tcp_store.cc`` (if the .so
is missing or older than the source) and returns a ``ctypes.CDLL``.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src")
_LIB = os.path.join(_HERE, "lib")

_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def _python_embed_flags() -> list:
    """Compile/link flags for components embedding CPython (the inference
    C API). Resolved from the running interpreter, not python3-config, so
    virtualenvs work."""
    import sysconfig

    include = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    version = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    flags = [f"-I{include}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags += [f"-lpython{version}"]
    return flags


# per-library extra build flags
_EXTRA_FLAGS = {
    "pd_inference_c": _python_embed_flags,
    # shm_open/shm_unlink live in librt on glibc < 2.34. Without the
    # explicit link the miss is invisible whenever some other loaded
    # library (torch, notably) already pulled librt into the process —
    # and fatal in fresh spawn children, where dlopen fails with
    # "undefined symbol: shm_open" and DataLoader shm workers die on
    # init. On glibc >= 2.34 librt is a stub, so the flag is harmless.
    "shm_ring": lambda: ["-lrt"],
}


def _sanitize_mode() -> str:
    """Sanitizer build mode for the native components — the TPU-native
    equivalent of the reference's cmake ``SANITIZER_TYPE`` option
    (reference CMakeLists.txt:270: Address/Thread/Undefined/...).
    ``PADDLE_TPU_SANITIZE=address|thread|undefined`` builds the .so with
    the matching -fsanitize instrumentation into a mode-suffixed file
    (the -O2 production .so is never reused for a sanitizer run, and
    vice versa). Loading an instrumented .so into a stock CPython needs
    the sanitizer runtime preloaded — see tests/test_native_sanitize.py
    for the LD_PRELOAD recipe."""
    mode = os.environ.get("PADDLE_TPU_SANITIZE", "").strip()
    allowed = ("", "address", "thread", "undefined")
    if mode not in allowed:
        raise ValueError(
            f"PADDLE_TPU_SANITIZE={mode!r}: expected one of "
            f"{[m for m in allowed if m]} (lowercase)")
    return mode


def _build(name: str, src_path: str, out_path: str, san: str = "") -> None:
    os.makedirs(_LIB, exist_ok=True)
    # Build into a temp file then atomically rename, so concurrent
    # processes never dlopen a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB)
    os.close(fd)
    extra = _EXTRA_FLAGS.get(name)
    san_flags = ([f"-fsanitize={san}", "-g", "-fno-omit-frame-pointer",
                  "-O1"] if san else ["-O2"])
    cmd = ["g++", "-shared", "-fPIC", "-std=c++17", "-pthread",
           *san_flags, src_path, "-o", tmp] + (extra() if extra else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build of {name} failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp, out_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and dlopen the native component ``name``."""
    san = _sanitize_mode()
    key = (name, san)
    with _lock:
        if key in _cache:
            return _cache[key]
        src_path = os.path.join(_SRC, f"{name}.cc")
        if not os.path.exists(src_path):
            raise FileNotFoundError(f"no native source for '{name}' "
                                    f"({src_path})")
        suffix = f".{san}.so" if san else ".so"
        out_path = os.path.join(_LIB, f"lib{name}{suffix}")
        # rebuild when the .so is older than the source OR this builder:
        # a flags change (e.g. a new _EXTRA_FLAGS entry) must invalidate
        # cached artifacts just like a source edit does
        stale_after = max(os.path.getmtime(src_path),
                          os.path.getmtime(os.path.abspath(__file__)))
        if (not os.path.exists(out_path)
                or os.path.getmtime(out_path) < stale_after):
            # pass the resolved mode: flags and filename must come from
            # the SAME read (a mislabeled cached .so would silently
            # report "clean" in every future sanitizer run)
            _build(name, src_path, out_path, san=san)
        lib = ctypes.CDLL(out_path)
        _cache[key] = lib
        return lib
