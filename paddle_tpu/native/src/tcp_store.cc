// TCP key-value rendezvous store — native core.
//
// Reference parity: paddle/phi/core/distributed/store/tcp_store.h:120 and
// tcp_utils.cc (the C++ TCPStore used for comm bootstrap, rpc rendezvous
// and barriers).  This is an original TPU-framework implementation: a
// thread-per-connection blocking server over a mutex+condvar KV map, with
// WAIT parking on the condvar instead of the reference's callback queue.
//
// Exposed as a plain C ABI (no pybind11 in this image) — Python binds via
// ctypes (paddle_tpu/native/tcp_store.py).
//
// Wire protocol (all integers little-endian):
//   request:  u8 op | u32 key_len | key bytes | payload
//     op=1 SET   payload: u64 val_len | val bytes
//     op=2 GET   payload: f64 timeout_s          (blocks until key exists)
//     op=3 ADD   payload: i64 delta              (atomic add on decimal value)
//     op=4 WAIT  payload: f64 timeout_s          (blocks until key exists)
//     op=5 CHECK payload: none                   (non-blocking existence)
//   response: u8 status (0 ok, 1 timeout/missing) | u64 len | bytes

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct KVState {
  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> map;
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_reply(int fd, uint8_t status, const std::string& body) {
  uint64_t len = body.size();
  std::string out;
  out.reserve(1 + 8 + body.size());
  out.push_back(static_cast<char>(status));
  out.append(reinterpret_cast<const char*>(&len), 8);
  out.append(body);
  return write_full(fd, out.data(), out.size());
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port), stop_(false) {}

  // Returns 0 on success, -1 when the listen socket could not be bound.
  int Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      ::close(listen_fd_);
      return -1;
    }
    if (::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      return -1;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return 0;
  }

  void Stop() {
    stop_.store(true);
    // Unblock accept() by connecting to ourselves, then close.
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd >= 0) {
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
      addr.sin_port = htons(static_cast<uint16_t>(port_));
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
      ::close(fd);
    }
    kv_.cv.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    ::close(listen_fd_);
    std::lock_guard<std::mutex> g(workers_mu_);
    for (auto& t : workers_)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (!stop_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0 || stop_.load()) {
        if (fd >= 0) ::close(fd);
        if (stop_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(workers_mu_);
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stop_.load()) {
      uint8_t op;
      uint32_t key_len;
      if (!read_full(fd, &op, 1) || !read_full(fd, &key_len, 4)) break;
      if (key_len > (1u << 20)) break;  // malformed
      std::string key(key_len, '\0');
      if (!read_full(fd, key.data(), key_len)) break;
      bool ok = true;
      switch (op) {
        case 1: {  // SET
          uint64_t vlen;
          if (!read_full(fd, &vlen, 8) || vlen > (1ull << 32)) {
            ok = false;
            break;
          }
          std::string val(vlen, '\0');
          if (!read_full(fd, val.data(), vlen)) {
            ok = false;
            break;
          }
          {
            std::lock_guard<std::mutex> g(kv_.mu);
            kv_.map[key] = std::move(val);
          }
          kv_.cv.notify_all();
          ok = send_reply(fd, 0, "");
          break;
        }
        case 2:    // GET (blocking)
        case 4: {  // WAIT
          double timeout_s;
          if (!read_full(fd, &timeout_s, 8)) {
            ok = false;
            break;
          }
          std::unique_lock<std::mutex> lk(kv_.mu);
          auto pred = [&] {
            return stop_.load() || kv_.map.count(key) > 0;
          };
          bool found;
          if (timeout_s <= 0) {
            kv_.cv.wait(lk, pred);
            found = kv_.map.count(key) > 0;
          } else {
            found = kv_.cv.wait_for(
                lk, std::chrono::duration<double>(timeout_s), pred);
            found = found && kv_.map.count(key) > 0;
          }
          if (!found) {
            lk.unlock();
            ok = send_reply(fd, 1, "");
          } else {
            std::string val = (op == 2) ? kv_.map[key] : "";
            lk.unlock();
            ok = send_reply(fd, 0, val);
          }
          break;
        }
        case 3: {  // ADD
          int64_t delta;
          if (!read_full(fd, &delta, 8)) {
            ok = false;
            break;
          }
          int64_t next;
          {
            std::lock_guard<std::mutex> g(kv_.mu);
            auto it = kv_.map.find(key);
            int64_t cur =
                (it == kv_.map.end()) ? 0 : std::strtoll(it->second.c_str(),
                                                         nullptr, 10);
            next = cur + delta;
            kv_.map[key] = std::to_string(next);
          }
          kv_.cv.notify_all();
          ok = send_reply(fd, 0, std::to_string(next));
          break;
        }
        case 5: {  // CHECK
          bool present;
          {
            std::lock_guard<std::mutex> g(kv_.mu);
            present = kv_.map.count(key) > 0;
          }
          ok = send_reply(fd, present ? 0 : 1, "");
          break;
        }
        default:
          ok = false;
      }
      if (!ok) break;
    }
    ::close(fd);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> stop_;
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::vector<std::thread> workers_;
  KVState kv_;
};

class StoreClient {
 public:
  // Returns nullptr-equivalent failure via Connect() == false.
  bool Connect(const char* ip, int port, double timeout_s) {
    auto deadline =
        std::chrono::steady_clock::now() +
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::duration<double>(timeout_s));
    while (true) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd_ < 0) return false;
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      if (::inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
        ::close(fd_);
        return false;
      }
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
          0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd_);
      if (std::chrono::steady_clock::now() >= deadline) return false;
      std::this_thread::sleep_for(std::chrono::milliseconds(200));
    }
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  // status: 0 ok, 1 timeout/missing, -1 transport error.
  int Request(uint8_t op, const std::string& key, const std::string& payload,
              std::string* out) {
    std::lock_guard<std::mutex> g(mu_);
    std::string req;
    uint32_t klen = static_cast<uint32_t>(key.size());
    req.push_back(static_cast<char>(op));
    req.append(reinterpret_cast<const char*>(&klen), 4);
    req.append(key);
    req.append(payload);
    if (!write_full(fd_, req.data(), req.size())) return -1;
    uint8_t status;
    uint64_t len;
    if (!read_full(fd_, &status, 1) || !read_full(fd_, &len, 8)) return -1;
    out->resize(len);
    if (len > 0 && !read_full(fd_, out->data(), len)) return -1;
    return status;
  }

 private:
  int fd_ = -1;
  std::mutex mu_;  // serialize request/response pairs across threads
};

}  // namespace

extern "C" {

void* pd_store_server_start(int port) {
  auto* s = new StoreServer(port);
  if (s->Start() != 0) {
    delete s;
    return nullptr;
  }
  return s;
}

void pd_store_server_stop(void* h) {
  auto* s = static_cast<StoreServer*>(h);
  s->Stop();
  delete s;
}

void* pd_store_client_connect(const char* ip, int port, double timeout_s) {
  auto* c = new StoreClient();
  if (!c->Connect(ip, port, timeout_s)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pd_store_client_free(void* h) { delete static_cast<StoreClient*>(h); }

int pd_store_set(void* h, const char* key, const uint8_t* data, int64_t len) {
  std::string payload;
  uint64_t l = static_cast<uint64_t>(len);
  payload.append(reinterpret_cast<const char*>(&l), 8);
  payload.append(reinterpret_cast<const char*>(data), len);
  std::string out;
  return static_cast<StoreClient*>(h)->Request(1, key, payload, &out);
}

// Returns value length (>=0) and malloc'd buffer in *out on success;
// -1 transport error; -2 timeout.
int64_t pd_store_get(void* h, const char* key, double timeout_s,
                     uint8_t** out) {
  std::string payload(reinterpret_cast<const char*>(&timeout_s), 8);
  std::string val;
  int st = static_cast<StoreClient*>(h)->Request(2, key, payload, &val);
  if (st == -1) return -1;
  if (st == 1) return -2;
  *out = static_cast<uint8_t*>(::malloc(val.size() ? val.size() : 1));
  std::memcpy(*out, val.data(), val.size());
  return static_cast<int64_t>(val.size());
}

void pd_store_free_buf(uint8_t* p) { ::free(p); }

// Returns new value after add, INT64_MIN on error.
int64_t pd_store_add(void* h, const char* key, int64_t delta) {
  std::string payload(reinterpret_cast<const char*>(&delta), 8);
  std::string out;
  int st = static_cast<StoreClient*>(h)->Request(3, key, payload, &out);
  if (st != 0) return INT64_MIN;
  return std::strtoll(out.c_str(), nullptr, 10);
}

// 0 = key present before deadline, 1 = timeout, -1 = transport error.
int pd_store_wait(void* h, const char* key, double timeout_s) {
  std::string payload(reinterpret_cast<const char*>(&timeout_s), 8);
  std::string out;
  return static_cast<StoreClient*>(h)->Request(4, key, payload, &out);
}

int pd_store_check(void* h, const char* key) {
  std::string out;
  return static_cast<StoreClient*>(h)->Request(5, key, "", &out);
}

}  // extern "C"
