// Parameter-server table engine — native core.
//
// Reference parity: the brpc parameter server's table layer
// (paddle/fluid/distributed/ps/table/: memory_sparse_table, dense tables,
// and the "accessor" fused embedding+optimizer update). This is an
// original implementation for the TPU framework: sharded hash-map sparse
// tables and flat dense tables whose PUSH applies the optimizer update
// (SGD / AdaGrad / Adam) in C++, so the Python transport layer never
// touches per-row math. Rows are initialized on first PULL with a
// deterministic per-key uniform(-range, range) draw (splitmix64 on
// key ^ seed) — no RNG state to serialize.
//
// C ABI only (ctypes-bound; no pybind11 in this image).

#include <fcntl.h>
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumShards = 16;

enum OptKind : int { kSGD = 0, kAdaGrad = 1, kAdam = 2 };

struct OptConfig {
  int kind = kSGD;
  float lr = 0.01f;
  float beta1 = 0.9f;
  float beta2 = 0.999f;
  float eps = 1e-8f;
};

// Optimizer state layout per row, appended after the `dim` weights:
//   SGD:     nothing
//   AdaGrad: dim (accumulated g^2)
//   Adam:    2*dim (m, v) + 1 (step count t)
int SlotWidth(const OptConfig& c, int dim) {
  switch (c.kind) {
    case kAdaGrad:
      return dim;
    case kAdam:
      return 2 * dim + 1;
    default:
      return 0;
  }
}

void ApplyUpdate(const OptConfig& c, int dim, float* w, float* slots,
                 const float* g) {
  switch (c.kind) {
    case kSGD:
      for (int i = 0; i < dim; ++i) w[i] -= c.lr * g[i];
      break;
    case kAdaGrad:
      for (int i = 0; i < dim; ++i) {
        slots[i] += g[i] * g[i];
        w[i] -= c.lr * g[i] / (std::sqrt(slots[i]) + c.eps);
      }
      break;
    case kAdam: {
      float* m = slots;
      float* v = slots + dim;
      float& t = slots[2 * dim];
      t += 1.0f;
      const float bc1 = 1.0f - std::pow(c.beta1, t);
      const float bc2 = 1.0f - std::pow(c.beta2, t);
      for (int i = 0; i < dim; ++i) {
        m[i] = c.beta1 * m[i] + (1.0f - c.beta1) * g[i];
        v[i] = c.beta2 * v[i] + (1.0f - c.beta2) * g[i] * g[i];
        const float mh = m[i] / bc1;
        const float vh = v[i] / bc2;
        w[i] -= c.lr * mh / (std::sqrt(vh) + c.eps);
      }
      break;
    }
  }
}

uint64_t SplitMix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

float UniformFromBits(uint64_t bits, float range) {
  // top 24 bits → [0, 1) → [-range, range)
  const float u = static_cast<float>(bits >> 40) / 16777216.0f;
  return (2.0f * u - 1.0f) * range;
}

class SparseTable {
 public:
  SparseTable(int dim, OptConfig opt, float init_range, uint64_t seed)
      : dim_(dim),
        opt_(opt),
        row_width_(dim + SlotWidth(opt, dim)),
        init_range_(init_range),
        seed_(seed) {}

  void Pull(const uint64_t* keys, int64_t n, float* out) {
    for (int64_t i = 0; i < n; ++i) {
      Shard& s = shard(keys[i]);
      std::lock_guard<std::mutex> g(s.mu);
      std::vector<float>& row = RowLocked(s, keys[i]);
      std::memcpy(out + i * dim_, row.data(), dim_ * sizeof(float));
    }
  }

  void Push(const uint64_t* keys, int64_t n, const float* grads) {
    for (int64_t i = 0; i < n; ++i) {
      Shard& s = shard(keys[i]);
      std::lock_guard<std::mutex> g(s.mu);
      std::vector<float>& row = RowLocked(s, keys[i]);
      ApplyUpdate(opt_, dim_, row.data(), row.data() + dim_,
                  grads + i * dim_);
    }
  }

  int64_t Size() const {
    int64_t total = 0;
    for (const auto& s : shards_) total += static_cast<int64_t>(s.map.size());
    return total;
  }

  bool Save(const char* path) const {
    std::FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    const uint64_t magic = 0x50535442ull;  // "PSTB"
    int64_t rows = Size();
    std::fwrite(&magic, 8, 1, f);
    std::fwrite(&dim_, sizeof(int), 1, f);
    std::fwrite(&row_width_, sizeof(int), 1, f);
    std::fwrite(&rows, 8, 1, f);
    for (const auto& s : shards_) {
      for (const auto& kv : s.map) {
        std::fwrite(&kv.first, 8, 1, f);
        std::fwrite(kv.second.data(), sizeof(float), row_width_, f);
      }
    }
    std::fclose(f);
    return true;
  }

  bool Load(const char* path) {
    std::FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    uint64_t magic = 0;
    int dim = 0, rw = 0;
    int64_t rows = 0;
    if (std::fread(&magic, 8, 1, f) != 1 || magic != 0x50535442ull ||
        std::fread(&dim, sizeof(int), 1, f) != 1 || dim != dim_ ||
        std::fread(&rw, sizeof(int), 1, f) != 1 || rw != row_width_ ||
        std::fread(&rows, 8, 1, f) != 1) {
      std::fclose(f);
      return false;
    }
    for (int64_t i = 0; i < rows; ++i) {
      uint64_t key;
      std::vector<float> row(row_width_);
      if (std::fread(&key, 8, 1, f) != 1 ||
          std::fread(row.data(), sizeof(float), row_width_, f) !=
              static_cast<size_t>(row_width_)) {
        std::fclose(f);
        return false;
      }
      Shard& s = shard(key);
      std::lock_guard<std::mutex> g(s.mu);
      s.map[key] = std::move(row);
    }
    std::fclose(f);
    return true;
  }

 private:
  struct Shard {
    std::mutex mu;
    std::unordered_map<uint64_t, std::vector<float>> map;
  };

  Shard& shard(uint64_t key) {
    return shards_[SplitMix64(key) % kNumShards];
  }

  std::vector<float>& RowLocked(Shard& s, uint64_t key) {
    auto it = s.map.find(key);
    if (it == s.map.end()) {
      std::vector<float> row(row_width_, 0.0f);
      for (int i = 0; i < dim_; ++i) {
        row[i] = UniformFromBits(SplitMix64(key ^ seed_ ^ (0x9E37ull * i)),
                                 init_range_);
      }
      it = s.map.emplace(key, std::move(row)).first;
    }
    return it->second;
  }

  const int dim_;
  const OptConfig opt_;
  const int row_width_;
  const float init_range_;
  const uint64_t seed_;
  Shard shards_[kNumShards];
};

class DenseTable {
 public:
  DenseTable(int64_t size, OptConfig opt)
      : opt_(opt),
        w_(size, 0.0f),
        slots_(static_cast<size_t>(size) *
                   (opt.kind == kAdaGrad ? 1 : (opt.kind == kAdam ? 2 : 0)) +
               (opt.kind == kAdam ? 1 : 0),
               0.0f) {}

  void SetValues(const float* vals) {
    std::lock_guard<std::mutex> g(mu_);
    std::memcpy(w_.data(), vals, w_.size() * sizeof(float));
  }

  void Pull(float* out) {
    std::lock_guard<std::mutex> g(mu_);
    std::memcpy(out, w_.data(), w_.size() * sizeof(float));
  }

  void Push(const float* grad) {
    std::lock_guard<std::mutex> g(mu_);
    const int64_t n = static_cast<int64_t>(w_.size());
    switch (opt_.kind) {
      case kSGD:
        for (int64_t i = 0; i < n; ++i) w_[i] -= opt_.lr * grad[i];
        break;
      case kAdaGrad:
        for (int64_t i = 0; i < n; ++i) {
          slots_[i] += grad[i] * grad[i];
          w_[i] -= opt_.lr * grad[i] / (std::sqrt(slots_[i]) + opt_.eps);
        }
        break;
      case kAdam: {
        float* m = slots_.data();
        float* v = slots_.data() + n;
        float& t = slots_[2 * n];
        t += 1.0f;
        const float bc1 = 1.0f - std::pow(opt_.beta1, t);
        const float bc2 = 1.0f - std::pow(opt_.beta2, t);
        for (int64_t i = 0; i < n; ++i) {
          m[i] = opt_.beta1 * m[i] + (1.0f - opt_.beta1) * grad[i];
          v[i] = opt_.beta2 * v[i] + (1.0f - opt_.beta2) * grad[i] * grad[i];
          w_[i] -= opt_.lr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + opt_.eps);
        }
        break;
      }
    }
  }

  int64_t Size() const { return static_cast<int64_t>(w_.size()); }

 private:
  const OptConfig opt_;
  std::mutex mu_;
  std::vector<float> w_;
  std::vector<float> slots_;
};

// Disk-backed sparse table (reference parity:
// paddle/fluid/distributed/ps/table/ssd_sparse_table.cc — hot rows in
// memory, cold rows on SSD via RocksDB). TPU-framework redesign without a
// RocksDB dependency: a single fixed-record file ([8-byte key | row floats]
// per slot) with an in-memory key->slot index, plus a FIFO-bounded hot-row
// cache. Rows evicted from the cache are written to their slot; rows pulled
// back in are read with pread. Reopening an existing file rebuilds the
// index by scanning records, so the table is durable across restarts.
class FileSparseTable {
 public:
  // validated 24-byte header: reopening with a mismatched dim/optimizer
  // must fail loudly, not stride the file at the wrong record size
  static constexpr uint64_t kMagic = 0x5053464255ull;  // "PSFBU"
  static constexpr int64_t kHeader = 24;  // magic u64 | dim i32 | rw i32 | pad

  FileSparseTable(int dim, OptConfig opt, float init_range, uint64_t seed,
                  const char* path, int64_t max_mem_rows)
      : dim_(dim),
        opt_(opt),
        row_width_(dim + SlotWidth(opt, dim)),
        rec_size_(8 + static_cast<int64_t>(row_width_) * sizeof(float)),
        init_range_(init_range),
        seed_(seed),
        max_mem_rows_(max_mem_rows > 0 ? max_mem_rows : 1) {
    fd_ = ::open(path, O_RDWR | O_CREAT, 0644);
    if (fd_ < 0) return;
    off_t end = ::lseek(fd_, 0, SEEK_END);
    char hdr[kHeader] = {};
    if (end == 0) {  // fresh file: stamp the header
      std::memcpy(hdr, &kMagic, 8);
      std::memcpy(hdr + 8, &dim_, 4);
      std::memcpy(hdr + 12, &row_width_, 4);
      if (::pwrite(fd_, hdr, kHeader, 0) != kHeader) {
        ::close(fd_);
        fd_ = -1;
        return;
      }
      return;
    }
    uint64_t magic = 0;
    int fdim = 0, frw = 0;
    if (::pread(fd_, hdr, kHeader, 0) != kHeader) {
      ::close(fd_);
      fd_ = -1;
      return;
    }
    std::memcpy(&magic, hdr, 8);
    std::memcpy(&fdim, hdr + 8, 4);
    std::memcpy(&frw, hdr + 12, 4);
    if (magic != kMagic || fdim != dim_ || frw != row_width_) {
      ::close(fd_);  // config mismatch -> loud open failure
      fd_ = -1;
      return;
    }
    // rebuild the key->slot index from the existing records
    int64_t n = (end - kHeader) / rec_size_;
    std::vector<char> rec(rec_size_);
    for (int64_t s = 0; s < n; ++s) {
      if (::pread(fd_, rec.data(), rec_size_, kHeader + s * rec_size_) !=
          rec_size_)
        break;
      uint64_t key;
      std::memcpy(&key, rec.data(), 8);
      slot_[key] = s;
    }
    next_slot_ = n;
  }

  ~FileSparseTable() {
    if (fd_ >= 0) {
      FlushLocked();
      ::close(fd_);
    }
  }

  bool ok() const { return fd_ >= 0; }

  void Pull(const uint64_t* keys, int64_t n, float* out) {
    std::lock_guard<std::mutex> g(mu_);
    for (int64_t i = 0; i < n; ++i) {
      std::vector<float>& row = RowLocked(keys[i]);
      std::memcpy(out + i * dim_, row.data(), dim_ * sizeof(float));
    }
  }

  void Push(const uint64_t* keys, int64_t n, const float* grads) {
    std::lock_guard<std::mutex> g(mu_);
    for (int64_t i = 0; i < n; ++i) {
      std::vector<float>& row = RowLocked(keys[i]);
      ApplyUpdate(opt_, dim_, row.data(), row.data() + dim_,
                  grads + i * dim_);
    }
  }

  int64_t Size() {
    std::lock_guard<std::mutex> g(mu_);
    int64_t on_disk = static_cast<int64_t>(slot_.size());
    for (const auto& kv : mem_)
      if (slot_.find(kv.first) == slot_.end()) ++on_disk;
    return on_disk;
  }

  int64_t MemRows() {
    std::lock_guard<std::mutex> g(mu_);
    return static_cast<int64_t>(mem_.size());
  }

  bool Flush() {
    std::lock_guard<std::mutex> g(mu_);
    return FlushLocked();
  }

 private:
  std::vector<float>& RowLocked(uint64_t key) {
    auto it = mem_.find(key);
    if (it != mem_.end()) return it->second;
    EvictLocked();
    std::vector<float> row(row_width_, 0.0f);
    auto st = slot_.find(key);
    if (st != slot_.end()) {
      ::pread(fd_, row.data(), row_width_ * sizeof(float),
              kHeader + st->second * rec_size_ + 8);
    } else {
      for (int i = 0; i < dim_; ++i) {
        row[i] = UniformFromBits(SplitMix64(key ^ seed_ ^ (0x9E37ull * i)),
                                 init_range_);
      }
    }
    it = mem_.emplace(key, std::move(row)).first;
    fifo_.push_back(key);
    return it->second;
  }

  void EvictLocked() {
    while (static_cast<int64_t>(mem_.size()) >= max_mem_rows_ &&
           !fifo_.empty()) {
      uint64_t victim = fifo_.front();
      fifo_.pop_front();
      auto it = mem_.find(victim);
      if (it == mem_.end()) continue;  // already evicted duplicate
      WriteRowLocked(victim, it->second);
      mem_.erase(it);
    }
  }

  void WriteRowLocked(uint64_t key, const std::vector<float>& row) {
    auto st = slot_.find(key);
    int64_t s = (st != slot_.end()) ? st->second : next_slot_++;
    slot_[key] = s;
    std::vector<char> rec(rec_size_);
    std::memcpy(rec.data(), &key, 8);
    std::memcpy(rec.data() + 8, row.data(), row_width_ * sizeof(float));
    if (::pwrite(fd_, rec.data(), rec_size_, kHeader + s * rec_size_) !=
        rec_size_) {
      // eviction write failed (ENOSPC, short write): the slot now holds
      // garbage. Poison the table — Flush() reports it and Python raises.
      io_error_ = true;
    }
  }

  bool FlushLocked() {
    for (const auto& kv : mem_) WriteRowLocked(kv.first, kv.second);
    return !io_error_ && ::fsync(fd_) == 0;
  }

  const int dim_;
  const OptConfig opt_;
  const int row_width_;
  const int64_t rec_size_;
  const float init_range_;
  const uint64_t seed_;
  const int64_t max_mem_rows_;
  int fd_ = -1;
  bool io_error_ = false;
  int64_t next_slot_ = 0;
  std::mutex mu_;
  std::unordered_map<uint64_t, std::vector<float>> mem_;
  std::unordered_map<uint64_t, int64_t> slot_;
  std::deque<uint64_t> fifo_;
};

}  // namespace

extern "C" {

void* pd_ps_sparse_create(int dim, int opt_kind, float lr, float beta1,
                          float beta2, float eps, float init_range,
                          uint64_t seed) {
  OptConfig c{opt_kind, lr, beta1, beta2, eps};
  return new SparseTable(dim, c, init_range, seed);
}

void pd_ps_sparse_free(void* h) { delete static_cast<SparseTable*>(h); }

void pd_ps_sparse_pull(void* h, const uint64_t* keys, int64_t n, float* out) {
  static_cast<SparseTable*>(h)->Pull(keys, n, out);
}

void pd_ps_sparse_push(void* h, const uint64_t* keys, int64_t n,
                       const float* grads) {
  static_cast<SparseTable*>(h)->Push(keys, n, grads);
}

int64_t pd_ps_sparse_size(void* h) {
  return static_cast<SparseTable*>(h)->Size();
}

int pd_ps_sparse_save(void* h, const char* path) {
  return static_cast<SparseTable*>(h)->Save(path) ? 0 : -1;
}

int pd_ps_sparse_load(void* h, const char* path) {
  return static_cast<SparseTable*>(h)->Load(path) ? 0 : -1;
}

void* pd_ps_dense_create(int64_t size, int opt_kind, float lr, float beta1,
                         float beta2, float eps) {
  OptConfig c{opt_kind, lr, beta1, beta2, eps};
  return new DenseTable(size, c);
}

void pd_ps_dense_free(void* h) { delete static_cast<DenseTable*>(h); }

void pd_ps_dense_set(void* h, const float* vals) {
  static_cast<DenseTable*>(h)->SetValues(vals);
}

void pd_ps_dense_pull(void* h, float* out) {
  static_cast<DenseTable*>(h)->Pull(out);
}

void pd_ps_dense_push(void* h, const float* grad) {
  static_cast<DenseTable*>(h)->Push(grad);
}

int64_t pd_ps_dense_size(void* h) {
  return static_cast<DenseTable*>(h)->Size();
}

void* pd_ps_file_create(int dim, int opt_kind, float lr, float beta1,
                        float beta2, float eps, float init_range,
                        uint64_t seed, const char* path,
                        int64_t max_mem_rows) {
  OptConfig opt{static_cast<OptKind>(opt_kind), lr, beta1, beta2, eps};
  auto* t = new FileSparseTable(dim, opt, init_range, seed, path,
                                max_mem_rows);
  if (!t->ok()) {
    delete t;
    return nullptr;
  }
  return t;
}

void pd_ps_file_free(void* h) { delete static_cast<FileSparseTable*>(h); }

void pd_ps_file_pull(void* h, const uint64_t* keys, int64_t n, float* out) {
  static_cast<FileSparseTable*>(h)->Pull(keys, n, out);
}

void pd_ps_file_push(void* h, const uint64_t* keys, int64_t n,
                     const float* grads) {
  static_cast<FileSparseTable*>(h)->Push(keys, n, grads);
}

int64_t pd_ps_file_size(void* h) {
  return static_cast<FileSparseTable*>(h)->Size();
}

int64_t pd_ps_file_mem_rows(void* h) {
  return static_cast<FileSparseTable*>(h)->MemRows();
}

int pd_ps_file_flush(void* h) {
  return static_cast<FileSparseTable*>(h)->Flush() ? 0 : 1;
}

}  // extern "C"
