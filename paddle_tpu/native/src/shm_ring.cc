// Shared-memory ring buffer — native core for DataLoader worker→trainer
// batch transfer.
//
// Reference parity: the shared-memory path of the multiprocess DataLoader
// (``use_shared_memory=True``: paddle/fluid/memory/allocation/
// mmap_allocator.cc + core._convert_to_tensor_list in
// python/paddle/fluid/dataloader/worker.py) — decoded batches travel
// through POSIX shared memory instead of being re-pickled through the
// multiprocessing result-queue pipe, removing one full copy and the pipe
// syscalls per batch.
//
// Design: one shm segment = header + byte ring of variable-size records
// (u64 length prefix, contiguous with wrap-around). A process-shared
// pthread mutex + two condvars (not-full / not-empty) in the header
// coordinate any number of producer/consumer processes. C ABI, ctypes.

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

namespace {

constexpr uint64_t kMagic = 0x50545348u;  // "PTSH"

struct Header {
  uint64_t magic;
  uint64_t capacity;   // ring byte capacity
  uint64_t head;       // read offset
  uint64_t tail;       // write offset
  uint64_t used;       // bytes in ring
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  uint64_t map_len;
  std::string name;
  bool owner;
};

timespec deadline_from(double timeout_s) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += static_cast<time_t>(timeout_s);
  ts.tv_nsec += static_cast<long>((timeout_s - static_cast<time_t>(timeout_s)) * 1e9);
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

void ring_copy_in(Ring* r, const uint8_t* src, uint64_t len) {
  Header* h = r->hdr;
  uint64_t pos = h->tail;
  uint64_t first = std::min(len, h->capacity - pos);
  std::memcpy(r->data + pos, src, first);
  if (len > first) std::memcpy(r->data, src + first, len - first);
  h->tail = (pos + len) % h->capacity;
}

void ring_copy_out(Ring* r, uint8_t* dst, uint64_t len) {
  Header* h = r->hdr;
  uint64_t pos = h->head;
  uint64_t first = std::min(len, h->capacity - pos);
  std::memcpy(dst, r->data + pos, first);
  if (len > first) std::memcpy(dst + len - (len - first), r->data, len - first);
  h->head = (pos + len) % h->capacity;
}

}  // namespace

extern "C" {

// Create (owner=1) or open (owner=0) a ring of `capacity` payload bytes.
// Returns NULL on failure.
void* pd_shm_ring_create(const char* name, uint64_t capacity, int owner) {
  uint64_t map_len = sizeof(Header) + capacity;
  int flags = owner ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (owner && ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!owner) {
    // adopt the creator's capacity
    struct stat st;
    if (fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_len = static_cast<uint64_t>(st.st_size);
    capacity = map_len - sizeof(Header);
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  auto* r = new Ring;
  r->hdr = static_cast<Header*>(mem);
  r->data = static_cast<uint8_t*>(mem) + sizeof(Header);
  r->map_len = map_len;
  r->name = name;
  r->owner = owner != 0;

  if (owner) {
    Header* h = r->hdr;
    h->capacity = capacity;
    h->head = h->tail = h->used = 0;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->not_full, &ca);
    pthread_cond_init(&h->not_empty, &ca);
    __atomic_store_n(&h->magic, kMagic, __ATOMIC_RELEASE);
  } else {
    // wait (briefly) for the creator to finish initializing
    for (int i = 0; i < 1000; ++i) {
      if (__atomic_load_n(&r->hdr->magic, __ATOMIC_ACQUIRE) == kMagic) break;
      usleep(1000);
    }
    if (r->hdr->magic != kMagic) {
      munmap(mem, map_len);
      delete r;
      return nullptr;
    }
  }
  return r;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // a worker died holding the lock; ring contents are suspect but the
    // structure is consistent enough to keep draining
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// 0 ok; -1 timeout; -2 record larger than capacity; -3 error.
int pd_shm_ring_push(void* handle, const uint8_t* payload, uint64_t len,
                     double timeout_s) {
  auto* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  uint64_t need = 8 + len;
  if (need > h->capacity) return -2;
  timespec dl = deadline_from(timeout_s);
  if (lock_robust(h) != 0) return -3;
  while (h->capacity - h->used < need) {
    int rc = pthread_cond_timedwait(&h->not_full, &h->mu, &dl);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
    if (rc != 0 && rc != EOWNERDEAD) {
      pthread_mutex_unlock(&h->mu);
      return -3;
    }
  }
  ring_copy_in(r, reinterpret_cast<const uint8_t*>(&len), 8);
  ring_copy_in(r, payload, len);
  h->used += need;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Returns record length and malloc'd buffer; -1 timeout; -3 error.
int64_t pd_shm_ring_pop(void* handle, uint8_t** out, double timeout_s) {
  auto* r = static_cast<Ring*>(handle);
  Header* h = r->hdr;
  timespec dl = deadline_from(timeout_s);
  if (lock_robust(h) != 0) return -3;
  while (h->used < 8) {
    int rc = pthread_cond_timedwait(&h->not_empty, &h->mu, &dl);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
    if (rc != 0 && rc != EOWNERDEAD) {
      pthread_mutex_unlock(&h->mu);
      return -3;
    }
  }
  uint64_t len = 0;
  ring_copy_out(r, reinterpret_cast<uint8_t*>(&len), 8);
  *out = static_cast<uint8_t*>(std::malloc(len ? len : 1));
  ring_copy_out(r, *out, len);
  h->used -= 8 + len;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

void pd_shm_ring_free_buf(uint8_t* p) { std::free(p); }

uint64_t pd_shm_ring_used(void* handle) {
  return static_cast<Ring*>(handle)->hdr->used;
}

void pd_shm_ring_close(void* handle) {
  auto* r = static_cast<Ring*>(handle);
  bool unlink = r->owner;
  std::string name = r->name;
  munmap(r->hdr, r->map_len);
  if (unlink) shm_unlink(name.c_str());
  delete r;
}

}  // extern "C"
