// C API for the inference predictor — native shim over embedded CPython.
//
// Reference parity: paddle/fluid/inference/capi_exp/ (the PD_* C ABI that
// lets C/C++/Go serving stacks drive AnalysisPredictor). Here the
// predictor executes StableHLO through JAX, so the C layer embeds a
// CPython interpreter (or joins the already-running one when loaded into
// a Python process) and marshals buffers to
// paddle_tpu.inference.capi_bridge. Zero business logic lives in C++.
//
// Thread model: every entry point takes the GIL via PyGILState_Ensure —
// safe to call from any thread of a C host program.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::mutex g_mu;
std::string g_last_error;
bool g_we_initialized = false;

void set_error(const char* where) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = where;
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      msg += ": ";
      msg += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  std::lock_guard<std::mutex> g(g_mu);
  g_last_error = msg;
}

PyObject* bridge() {
  // fresh import each call is a dict lookup after the first time
  return PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
}

struct GIL {
  PyGILState_STATE st;
  GIL() : st(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

// Initialize (or join) the interpreter. `pythonpath_prepend` may be NULL;
// pass the repo root when driving from a standalone C program.
int PD_Init(const char* pythonpath_prepend) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    // the embedded interpreter starts on this thread holding the GIL;
    // release it so GIL{} guards work uniformly afterwards
    PyEval_SaveThread();
  }
  GIL gil;
  if (pythonpath_prepend && *pythonpath_prepend) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* p = PyUnicode_FromString(pythonpath_prepend);
    if (sys_path && p) PyList_Insert(sys_path, 0, p);
    Py_XDECREF(p);
  }
  PyObject* m = bridge();
  if (!m) {
    set_error("PD_Init: import paddle_tpu.inference.capi_bridge");
    return -1;
  }
  Py_DECREF(m);
  return 0;
}

const char* PD_GetLastError(void) {
  std::lock_guard<std::mutex> g(g_mu);
  return g_last_error.c_str();
}

// Returns predictor handle > 0, or 0 on failure.
int64_t PD_PredictorCreate(const char* model_prefix, const char* device) {
  GIL gil;
  PyObject* m = bridge();
  if (!m) {
    set_error("import bridge");
    return 0;
  }
  PyObject* r = PyObject_CallMethod(m, "create_predictor", "ss", model_prefix,
                                    device ? device : "tpu");
  Py_DECREF(m);
  if (!r) {
    set_error("PD_PredictorCreate");
    return 0;
  }
  int64_t h = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return h;
}

void PD_PredictorDestroy(int64_t handle) {
  GIL gil;
  PyObject* m = bridge();
  if (!m) return;
  PyObject* r = PyObject_CallMethod(m, "destroy_predictor", "L", handle);
  Py_XDECREF(r);
  Py_DECREF(m);
  if (!r) PyErr_Clear();
}

// Writes up to `max_names` NUL-terminated names into user buffers of
// `name_cap` bytes each; returns input count or -1.
int PD_PredictorGetInputNames(int64_t handle, char** names, int max_names,
                              int name_cap) {
  GIL gil;
  PyObject* m = bridge();
  if (!m) {
    set_error("import bridge");
    return -1;
  }
  PyObject* r = PyObject_CallMethod(m, "input_names", "L", handle);
  Py_DECREF(m);
  if (!r) {
    set_error("PD_PredictorGetInputNames");
    return -1;
  }
  int n = static_cast<int>(PyList_Size(r));
  for (int i = 0; i < n && i < max_names; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    std::strncpy(names[i], s ? s : "", name_cap - 1);
    names[i][name_cap - 1] = '\0';
  }
  Py_DECREF(r);
  return n;
}

// dtype: "float32", "int64", ... matching numpy names.
int PD_PredictorSetInput(int64_t handle, const char* name, const void* data,
                         const int64_t* dims, int ndim, const char* dtype) {
  GIL gil;
  int64_t numel = 1;
  for (int i = 0; i < ndim; ++i) numel *= dims[i];
  int64_t itemsize;
  if (std::strcmp(dtype, "float64") == 0 || std::strcmp(dtype, "int64") == 0 ||
      std::strcmp(dtype, "uint64") == 0 || std::strcmp(dtype, "complex64") == 0)
    itemsize = 8;
  else if (std::strcmp(dtype, "float32") == 0 ||
           std::strcmp(dtype, "int32") == 0 ||
           std::strcmp(dtype, "uint32") == 0)
    itemsize = 4;
  else if (std::strcmp(dtype, "float16") == 0 ||
           std::strcmp(dtype, "bfloat16") == 0 ||
           std::strcmp(dtype, "int16") == 0 ||
           std::strcmp(dtype, "uint16") == 0)
    itemsize = 2;
  else if (std::strcmp(dtype, "int8") == 0 || std::strcmp(dtype, "uint8") == 0 ||
           std::strcmp(dtype, "bool") == 0)
    itemsize = 1;
  else {
    std::lock_guard<std::mutex> g(g_mu);
    g_last_error = std::string("PD_PredictorSetInput: unknown dtype ") + dtype;
    return -1;
  }
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)), numel * itemsize,
      PyBUF_READ);
  PyObject* dimlist = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SetItem(dimlist, i, PyLong_FromLongLong(dims[i]));
  PyObject* m = bridge();
  PyObject* r = m ? PyObject_CallMethod(m, "set_input", "LsOOs", handle, name,
                                        mv, dimlist, dtype)
                  : nullptr;
  Py_XDECREF(m);
  Py_XDECREF(mv);
  Py_XDECREF(dimlist);
  if (!r) {
    set_error("PD_PredictorSetInput");
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

// Returns number of outputs, or -1.
int PD_PredictorRun(int64_t handle) {
  GIL gil;
  PyObject* m = bridge();
  PyObject* r =
      m ? PyObject_CallMethod(m, "run", "L", handle) : nullptr;
  Py_XDECREF(m);
  if (!r) {
    set_error("PD_PredictorRun");
    return -1;
  }
  int n = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return n;
}

// Returns ndim (and fills dims up to max_ndim), or -1.
int PD_PredictorGetOutputDims(int64_t handle, int idx, int64_t* dims,
                              int max_ndim) {
  GIL gil;
  PyObject* m = bridge();
  PyObject* r =
      m ? PyObject_CallMethod(m, "output_dims", "Li", handle, idx) : nullptr;
  Py_XDECREF(m);
  if (!r) {
    set_error("PD_PredictorGetOutputDims");
    return -1;
  }
  int n = static_cast<int>(PyList_Size(r));
  for (int i = 0; i < n && i < max_ndim; ++i)
    dims[i] = PyLong_AsLongLong(PyList_GetItem(r, i));
  Py_DECREF(r);
  return n;
}

// Writes the numpy dtype name into `out` (cap bytes); returns 0 or -1.
int PD_PredictorGetOutputDtype(int64_t handle, int idx, char* out, int cap) {
  GIL gil;
  PyObject* m = bridge();
  PyObject* r =
      m ? PyObject_CallMethod(m, "output_dtype", "Li", handle, idx) : nullptr;
  Py_XDECREF(m);
  if (!r) {
    set_error("PD_PredictorGetOutputDtype");
    return -1;
  }
  const char* s = PyUnicode_AsUTF8(r);
  std::strncpy(out, s ? s : "", cap - 1);
  out[cap - 1] = '\0';
  Py_DECREF(r);
  return 0;
}

// Copies output idx into `out` (must hold the full tensor). Returns bytes
// written, or -1.
int64_t PD_PredictorCopyOutput(int64_t handle, int idx, void* out,
                               int64_t out_bytes) {
  GIL gil;
  PyObject* mv = PyMemoryView_FromMemory(static_cast<char*>(out), out_bytes,
                                         PyBUF_WRITE);
  PyObject* m = bridge();
  PyObject* r = m ? PyObject_CallMethod(m, "copy_output", "LiO", handle, idx,
                                        mv)
                  : nullptr;
  Py_XDECREF(m);
  Py_XDECREF(mv);
  if (!r) {
    set_error("PD_PredictorCopyOutput");
    return -1;
  }
  int64_t n = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return n;
}

void PD_Finalize(void) {
  if (g_we_initialized && Py_IsInitialized()) {
    PyGILState_Ensure();
    Py_Finalize();
    g_we_initialized = false;
  }
}

}  // extern "C"
