"""The eager Tensor.

TPU-native counterpart of the reference's eager ``Tensor`` + ``AutogradMeta``
(``paddle/fluid/eager/autograd_meta.h``, ``paddle/phi/core/dense_tensor.h:38``).

Design: a Tensor is a thin mutable cell around an immutable ``jax.Array`` (or a
JAX tracer, when running under ``paddle_tpu.jit``). Autograd metadata hangs off
the cell exactly like the reference's AutogradMeta hangs off its Tensor:
``stop_gradient`` (True by default, False for Parameters), ``grad`` (leaf
accumulation target), and ``_grad_node`` (the producing GradNode, the tape
edge). Because the payload may be a tracer, the whole eager engine is
*traceable*: running the same imperative code under jax.jit compiles the full
step into one XLA program — the TPU answer to the reference's separate
eager/static engines.
"""
from __future__ import annotations

import itertools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes

_uid_counter = itertools.count()

# Set by paddle_tpu.jit while tracing a compiled step: records Tensor._value
# writes so mutated state can be functionalized (returned from the jitted fn).
_trace_recorders: list = []


class Tensor:
    """Eager tensor with autograd metadata (reference: eager Tensor +
    AutogradMeta, paddle/fluid/eager/autograd_meta.h)."""

    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_output_index",
        "name",
        "_hooks",
        "_uid",
        "dist_attr",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._grad_node = None  # GradNode that produced this tensor
        self._output_index = 0  # which output slot of that node
        self.name = name or f"tensor_{next(_uid_counter)}"
        self._hooks = None
        self._uid = next(_uid_counter)
        self.dist_attr = None  # set by paddle_tpu.distributed.shard_tensor

    # ------------------------------------------------------------------ meta
    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, new):
        self._set_value(new)

    def _set_value(self, new):
        """In-place payload replacement (reference: inplace ops / ShareDataWith).

        Under a jit trace this is recorded so the mutation becomes a
        functional output of the compiled program.
        """
        if isinstance(new, Tensor):
            new = new._value
        self._value = new
        for rec in _trace_recorders:
            rec.record_write(self)

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def place(self) -> str:
        v = self._value
        if isinstance(v, jax.core.Tracer):
            return "traced"
        try:
            dev = list(v.devices())[0]
            return f"{dev.platform}:{dev.id}"
        except Exception:
            return "cpu"

    def numel(self):
        return self.size

    # -------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        """reference: paddle Tensor.backward -> egr::Backward (eager/backward.cc:423)."""
        from .autograd import backward as _backward

        _backward([self], [grad_tensor] if grad_tensor is not None else None, retain_graph)

    def register_hook(self, hook):
        """Grad hook, run when this tensor's gradient is computed
        (reference: eager/hooks.h)."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        idx = len(self._hooks) - 1
        tensor = self

        class _Removable:
            def remove(self):
                tensor._hooks[idx] = None

        return _Removable()

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self._value))
        else:
            self.grad = None

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True)

    # ------------------------------------------------------------ conversion
    def numpy(self):
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dtype) -> "Tensor":
        from .ops import cast

        return cast(self, dtype)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def clone(self) -> "Tensor":
        from .ops import assign

        return assign(self)

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def block_until_ready(self) -> "Tensor":
        if hasattr(self._value, "block_until_ready"):
            self._value.block_until_ready()
        return self

    # ------------------------------------------------------------------ repr
    def __repr__(self):
        if isinstance(self._value, jax.core.Tracer):
            return f"Tensor(traced, shape={self.shape}, dtype={self.dtype})"
        return (
            f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
            f"stop_gradient={self.stop_gradient},\n       {np.asarray(self._value)!r})"
        )

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        """Iterate the first axis (reference: Tensor.__iter__ slicing
        along axis 0). Without this, Python falls back to the legacy
        __getitem__(0,1,2,...) protocol, which never terminates on a jax
        backend — jax CLAMPS out-of-range integer indices instead of
        raising IndexError (found r5: ``for v in tensor`` span forever).

        The 0-d check runs EAGERLY (iter() raises, like numpy), not on
        first next() — duck-typing callers probe iterability via iter().
        """
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")

        def _gen(n):
            for i in range(n):
                yield self[i]

        return _gen(self._value.shape[0])

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __hash__(self):
        # Identity hash: tensors are dict keys by object identity (uid can be
        # rebound by in-place ops, see autograd.engine.inplace_rebind).
        return id(self)

    # Operator overloads are patched in by paddle_tpu.ops (monkey-patch, like
    # the reference's eager_math_op_patch.cc).

    # Make numpy coercion explicit
    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr


Parameter_doc = """A Parameter is a Tensor with stop_gradient=False plus an
optimize flag (reference: python/paddle/fluid/framework.py Parameter)."""


class Parameter(Tensor):
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, value, trainable: bool = True, name: Optional[str] = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """reference: paddle.to_tensor (python/paddle/tensor/creation.py).

    Examples:
        >>> x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        >>> x.shape
        [2, 2]
        >>> y = paddle.to_tensor(np.arange(4), dtype="float32")
        >>> float(y.sum())
        6.0
    """
    del place  # device placement is managed by jax / shardings
    dtype = dtypes.convert_dtype(dtype)
    if isinstance(data, Tensor):
        value = data._value
        if dtype is not None and value.dtype != np.dtype(dtype):
            value = value.astype(dtype)
        return Tensor(value, stop_gradient=stop_gradient)
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        value = data if dtype is None else data.astype(dtype)
        return Tensor(value, stop_gradient=stop_gradient)
    arr = np.asarray(data)
    if dtype is None and arr.dtype == np.float64:
        # Match paddle's default fp32 (and TPU sanity): python floats -> f32;
        # python ints stay int64 (numpy default), matching paddle.
        arr = arr.astype(np.float32)
    value = jnp.asarray(arr, dtype=dtype)
    return Tensor(value, stop_gradient=stop_gradient)
