"""paddle.text parity: viterbi decoding (+ dataset surface note).

Reference parity: python/paddle/text/ — ``viterbi_decode``/``ViterbiDecoder``
(viterbi_decode.py) implemented as a lax.scan DP (jit-able, batched);
the ``datasets`` subpackage (Imdb/Imikolov/Movielens/...) is download-based
and cannot operate in a zero-egress image — constructors raise with that
explanation rather than pretending.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer_base import Layer
from ..ops._apply import apply_op, ensure_tensor
from ..tensor import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets",
           "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """reference: text/viterbi_decode.py viterbi_decode.

    potentials [B, T, N] emissions, transition_params [N, N] (with optional
    BOS=N-2/EOS=N-1 rows when include_bos_eos_tag), lengths [B].
    Returns (scores [B], paths [B, T]).
    """
    pot = ensure_tensor(potentials)
    trans = ensure_tensor(transition_params)
    lens = ensure_tensor(lengths)
    B, T, N = pot.shape

    def fn(p, tr, ln):
        bos, eos = N - 2, N - 1

        init = p[:, 0, :]
        if include_bos_eos_tag:
            init = init + tr[bos][None, :]

        def step(carry, t):
            alpha, hist_dummy = carry
            # scores[b, prev, cur] = alpha[b, prev] + tr[prev, cur] + emit
            scores = alpha[:, :, None] + tr[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)  # [B, N]
            best_score = jnp.max(scores, axis=1) + p[:, t, :]
            # frozen past sequence end
            active = (t < ln)[:, None]
            alpha_new = jnp.where(active, best_score, alpha)
            bp = jnp.where(active, best_prev,
                           jnp.arange(N)[None, :].astype(best_prev.dtype))
            return (alpha_new, hist_dummy), bp

        (alpha, _), backptrs = jax.lax.scan(
            step, (init, jnp.zeros((), jnp.int32)), jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + tr[:, eos][None, :]
        last_tag = jnp.argmax(alpha, axis=-1)  # [B]
        scores = jnp.max(alpha, axis=-1)

        # walk back through [T-1, B, N] pointers
        def back(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # reverse scan: final carry = tag at t=0; ys[i] = tag at t=i+1
        first_tag, tags_rest = jax.lax.scan(back, last_tag, backptrs,
                                            reverse=True)
        paths = jnp.concatenate(
            [first_tag[:, None], jnp.swapaxes(tags_rest, 0, 1)],
            axis=1)  # [B, T]
        # positions past each length take the last valid tag (ref pads)
        idx = jnp.arange(T)[None, :]
        paths = jnp.where(idx < ln[:, None], paths,
                          jnp.take_along_axis(
                              paths, jnp.maximum(ln - 1, 0)[:, None],
                              axis=1))
        return scores, paths

    out = apply_op(lambda pv, tv: fn(pv, tv, lens._value.astype("int32")),
                   [pot, trans], name="viterbi_decode")
    return out


class ViterbiDecoder(Layer):
    """reference: text/viterbi_decode.py ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _ZeroEgressDataset:
    def __init__(self, *a, **k):
        raise RuntimeError(
            f"{type(self).__name__} downloads its corpus from the network; "
            "this environment is zero-egress. Provide the files locally and "
            "use paddle_tpu.io.Dataset to wrap them.")


class datasets:
    class Imdb(_ZeroEgressDataset):
        pass

    class Imikolov(_ZeroEgressDataset):
        pass

    class Movielens(_ZeroEgressDataset):
        pass

    class UCIHousing(_ZeroEgressDataset):
        pass

    class WMT14(_ZeroEgressDataset):
        pass

    class WMT16(_ZeroEgressDataset):
        pass

    class Conll05st(_ZeroEgressDataset):
        pass


# reference exports the dataset classes at paddle.text top level too
Imdb = datasets.Imdb
Imikolov = datasets.Imikolov
Movielens = datasets.Movielens
UCIHousing = datasets.UCIHousing
WMT14 = datasets.WMT14
WMT16 = datasets.WMT16
Conll05st = datasets.Conll05st
